"""Shard-wide observability: metrics registry, trace ids, event journal.

The reference manatee has none of this — its operators reconstruct a
failover by grepping per-peer bunyan logs (PAPER.md §0).  This package
gives every component in the peer three shared primitives:

- a process-wide metrics **registry** (`get_registry()`): counters,
  gauges, and monotonic-clock latency histograms with fixed buckets,
  rendered through the shared Prometheus text builder by the status
  server's ``GET /metrics`` (and coordd's);
- **trace ids** (`new_trace_id()` / `bind_trace()`): every
  state-machine transition mints one; it rides the coord RPC frames,
  the cluster-state object itself (so *other* peers' reactions to the
  transition carry the initiator's id), every bunyan log record, and
  the pg/backup operations the transition causes;
- an in-memory ring-buffer event **journal** (`get_journal()`):
  transition begun/committed, role changes, coord session events,
  probe state flips, restore start/finish — exposed as ``GET /events``
  per peer and merged shard-wide by ``manatee-adm events``.

Everything here is stdlib-only and allocation-light: observability must
never be able to hurt HA.
"""

from manatee_tpu.obs.journal import EventJournal, get_journal, set_peer
from manatee_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
)
from manatee_tpu.obs.trace import (
    TraceLogFilter,
    bind_trace,
    current_trace,
    ensure_trace,
    new_trace_id,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "EventJournal",
    "Gauge",
    "Histogram",
    "Registry",
    "TraceLogFilter",
    "bind_trace",
    "current_trace",
    "ensure_trace",
    "get_journal",
    "get_registry",
    "new_trace_id",
    "set_peer",
]
