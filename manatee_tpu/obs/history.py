"""On-disk metric history: an append-only crash-safe segment ring.

The registry (`obs/metrics.py`) answers "what is the value NOW"; a
fleet operator asking "which shard burned its error budget this week"
needs the values over time without running a Prometheus stack.  This
module persists periodic snapshots of the whole metrics registry into
JSONL segments under one directory, with the coordd oplog's crash
discipline:

- one record per line, appended then flushed + fsynced, so the only
  thing a crash can cost is the FINAL line (torn tail — the
  recoverable, never-acked signature, `manatee-adm doctor` notes it
  but does not count it as damage);
- segments roll over after a fixed record count and are named by the
  first record's sequence number, so continuity is checkable from the
  names alone;
- retention is bounded: the oldest segments are deleted once the ring
  exceeds its segment budget (observability must never grow without
  bound next to an HA daemon's data).

Snapshot records are deliberately small: counters and gauges dump
their samples, histograms dump per-series ``count``/``sum`` only
(rates and means are what a trend line needs; bucket vectors would
multiply the snapshot size for no operator question this layer
answers).

Serving follows the spans/events pattern: :func:`history_http_reply`
is the whole ``GET /history?since=SEQ&limit=N`` endpoint minus the web
framework, shared by every daemon listener that mounts it.

The append seam carries the ``obs.history.append`` failpoint; the
crash-recovery sweep crashes a writer mid-append and asserts the
segments come back doctor-clean.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from pathlib import Path

from manatee_tpu.obs.causal import hlc_now
from manatee_tpu.obs.journal import _iso_ms
from manatee_tpu.obs.metrics import Registry, get_registry
from manatee_tpu.obs.spans import parse_page_query

log = logging.getLogger("manatee.history")

SEGMENT_PREFIX = "history-"
DEFAULT_SEGMENT_RECORDS = 256
DEFAULT_KEEP_SEGMENTS = 8
DEFAULT_INTERVAL = 10.0


def segment_name(start_seq: int) -> str:
    return "%s%016d.jsonl" % (SEGMENT_PREFIX, start_seq)


def parse_segment_name(p) -> int | None:
    """Start seq from a history segment path, or None when the name is
    not a history segment at all."""
    name = Path(p).name
    if not (name.startswith(SEGMENT_PREFIX) and name.endswith(".jsonl")):
        return None
    body = name[len(SEGMENT_PREFIX):-len(".jsonl")]
    if not body.isdigit():
        return None
    return int(body)


def dump_registry(reg: Registry) -> dict:
    """One JSON-able snapshot of every instrument's current values."""
    out: dict[str, dict] = {}
    for inst in reg.instruments():
        if inst.kind in ("counter", "gauge"):
            out[inst.name] = {
                "kind": inst.kind,
                "samples": [[labels, v] for labels, v in inst.samples()],
            }
        else:
            out[inst.name] = {
                "kind": "histogram",
                "series": [[labels, {"count": s["count"],
                                     "sum": round(s["sum"], 6)}]
                           for labels, s in inst.series()],
            }
    return out


def list_segments(directory) -> list[Path]:
    """History segment paths under *directory*, oldest first."""
    segs = []
    for p in Path(directory).glob(SEGMENT_PREFIX + "*.jsonl"):
        seq = parse_segment_name(p)
        if seq is not None:
            segs.append((seq, p))
    return [p for _seq, p in sorted(segs)]


def read_records(directory) -> list[dict]:
    """Every parseable snapshot record, oldest first.  A torn final
    line of the final segment (crash mid-append) is skipped — that
    record was never durable; mid-stream garbage is skipped too (the
    doctor, not the reader, is the integrity judge)."""
    out: list[dict] = []
    segs = list_segments(directory)
    for p in segs:
        try:
            text = p.read_text()
        except OSError:
            continue
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "seq" in rec:
                out.append(rec)
    return out


class MetricsHistory:
    """The writer: appends registry snapshots to the segment ring.

    Everything runs on the event loop thread; the file writes are tiny
    (one JSON line per interval) and fsynced so the worst a crash can
    lose is the line being appended.
    """

    def __init__(self, directory, *,
                 segment_records: int = DEFAULT_SEGMENT_RECORDS,
                 keep_segments: int = DEFAULT_KEEP_SEGMENTS,
                 registry: Registry | None = None):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_records = max(1, int(segment_records))
        self.keep_segments = max(1, int(keep_segments))
        self._registry = registry or get_registry()
        self._fh = None
        self._fh_records = 0
        # recovery, coordd-style: a torn final line (crash mid-append)
        # was never durable — truncate it so a resumed writer never
        # appends a valid record AFTER garbage; then resume after the
        # last durable record, so seq continuity survives the crash
        self._truncate_torn_tail()
        recs = read_records(self.dir)
        self._seq = recs[-1]["seq"] if recs else 0

    def _truncate_torn_tail(self) -> None:
        segs = list_segments(self.dir)
        if not segs:
            return
        last = segs[-1]
        try:
            raw = last.read_bytes()
        except OSError:
            return
        # a durable record always ends in "\n"; anything after the
        # last newline is the torn write
        head, _sep, tail = raw.rpartition(b"\n")
        if not tail.strip():
            return
        try:
            json.loads(tail)
            torn = False
        except ValueError:
            torn = True
        with open(last, "r+b") as fh:
            if torn:
                fh.truncate(len(head) + 1 if head else 0)
            else:
                # a complete record missing only its "\n": finish the
                # line, or the next append would fuse with it
                fh.seek(0, os.SEEK_END)
                fh.write(b"\n")
            fh.flush()
            os.fsync(fh.fileno())

    # -- writing --

    async def append(self) -> dict:
        """Snapshot the registry and append one record (the
        ``obs.history.append`` seam).

        The snapshot happens on the loop (registry reads are loop-side
        state); the rotate+write+fsync tail goes through ``to_thread``
        so the per-record fsync never stalls the loop on a slow disk.
        HistoryRecorder._run is the only caller, so the file handle is
        never raced."""
        from manatee_tpu import faults
        await faults.point("obs.history.append")
        self._seq += 1
        ts = round(time.time(), 3)
        rec = {"seq": self._seq, "ts": ts, "time": _iso_ms(ts),
               "hlc": hlc_now(),
               "metrics": dump_registry(self._registry)}
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        await asyncio.to_thread(self._append_durable, line)
        return rec

    def _append_durable(self, line: str) -> None:
        if self._fh is None or self._fh_records >= self.segment_records:
            self._rotate()
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh_records += 1

    def _rotate(self) -> None:
        """Close the current segment, open a fresh one named by the
        next record's seq, and drop segments beyond the retention
        budget (oldest first)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = self.dir / segment_name(self._seq)
        self._fh = open(path, "a")
        self._fh_records = 0
        segs = list_segments(self.dir)
        while len(segs) > self.keep_segments:
            victim = segs.pop(0)
            try:
                victim.unlink()
            except OSError:
                break

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # -- reading --

    def records(self, *, since: int = 0, limit: int | None = None
                ) -> list[dict]:
        """Records with seq > *since*, oldest first, newest *limit* —
        the /events pagination contract over the on-disk ring."""
        out = [r for r in read_records(self.dir) if r["seq"] > since]
        if limit is not None and limit >= 0:
            # NOT out[-limit:]: -0 slices the whole list (journal.py)
            out = out[-limit:] if limit else []
        return out


class HistoryRecorder:
    """The periodic snapshot task daemons embed: every *interval*
    seconds, append one registry snapshot.  start()/stop() mirror the
    other daemon sub-tasks."""

    def __init__(self, history: MetricsHistory,
                 interval: float = DEFAULT_INTERVAL):
        self.history = history
        self.interval = float(interval)
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self.history.close()

    async def _run(self) -> None:
        while True:
            try:
                await self.history.append()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # history must never hurt HA: a full disk degrades the
                # trend line, not the daemon
                log.warning("history append failed: %r", e)
            await asyncio.sleep(self.interval)


# ---- process singleton (daemon wiring; None until enabled) ----

_HISTORY: MetricsHistory | None = None


def init_history(directory, **kw) -> MetricsHistory:
    """Enable the on-disk history for this process (config wiring).
    Returns the singleton the daemon's listener serves at /history."""
    global _HISTORY
    _HISTORY = MetricsHistory(directory, **kw)
    return _HISTORY


def get_history() -> MetricsHistory | None:
    """The process-wide history ring, or None when not enabled."""
    return _HISTORY


def history_http_reply(history: MetricsHistory | None, query
                       ) -> tuple[dict, int]:
    """The WHOLE ``GET /history`` endpoint minus the web framework:
    (json body, HTTP status), shared by every daemon listener that
    mounts it (status server, backup REST server, coordd metrics,
    the prober) so the contract cannot drift."""
    if history is None:
        return {"error": "metric history is not enabled on this "
                         "daemon (set historyDir in its config)"}, 404
    try:
        since, limit = parse_page_query(query)
    except ValueError:
        return {"error": "since/limit must be integers"}, 400
    return {
        "now": round(time.time(), 3),
        "hlc": hlc_now(),
        "dir": str(history.dir),
        "records": history.records(since=since, limit=limit),
    }, 200
