"""Standard process self-metrics (the Prometheus client conventions):
start time, CPU, RSS, open fds — refreshed lazily at scrape time.

`manatee-adm top` and the history ring (obs/history.py) read resource
trends per daemon from these; nothing in the control plane's hot path
pays for them — each HTTP listener calls :func:`refresh_process_metrics`
once per ``/metrics`` scrape (and the history recorder gets them for
free because the recorder snapshots whatever the registry holds).

Sources are stdlib-only: ``resource.getrusage`` for CPU (portable) and
``/proc/self`` for RSS/fds/start time where available (Linux); absent
``/proc`` the gauges simply stay unset rather than guessing.
"""

from __future__ import annotations

import os
import resource
import time

from manatee_tpu.obs.metrics import get_registry

_REG = get_registry()
_START_TIME = _REG.gauge(
    "process_start_time_seconds",
    "unix time the process started")
_CPU = _REG.counter(
    "process_cpu_seconds_total",
    "user + system CPU time consumed")
_RSS = _REG.gauge(
    "process_resident_memory_bytes",
    "resident set size")
_FDS = _REG.gauge(
    "process_open_fds",
    "open file descriptors")

_cpu_last = 0.0
_start_set = False


def _proc_start_time() -> float:
    """Kernel-accounted start time: field 22 of /proc/self/stat is
    clock ticks after boot; boot = now - /proc/uptime."""
    with open("/proc/self/stat") as fh:
        stat = fh.read()
    # comm (field 2) may contain spaces/parens: split after the
    # closing paren
    fields = stat.rsplit(")", 1)[1].split()
    ticks = float(fields[19])          # starttime is field 22 overall
    hz = os.sysconf("SC_CLK_TCK")
    with open("/proc/uptime") as fh:
        uptime = float(fh.read().split()[0])
    return time.time() - uptime + ticks / hz


def refresh_process_metrics() -> None:
    """Bring the self-metrics up to date (scrape-time, best-effort:
    introspection must never fail a scrape)."""
    global _cpu_last, _start_set
    if not _start_set:
        _start_set = True
        try:
            _START_TIME.set(_proc_start_time())
        except (OSError, IndexError, ValueError):
            _START_TIME.set(time.time())   # no /proc: import-ish time
    ru = resource.getrusage(resource.RUSAGE_SELF)
    cpu = ru.ru_utime + ru.ru_stime
    if cpu > _cpu_last:
        _CPU.inc(cpu - _cpu_last)
        _cpu_last = cpu
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        _RSS.set(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, IndexError, ValueError):
        # macOS fallback: ru_maxrss is bytes there (kbytes on Linux,
        # where /proc served us already)
        _RSS.set(ru.ru_maxrss)
    try:
        _FDS.set(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass


def process_instruments():
    """The four self-metrics instruments, for listeners that render a
    hand-built exposition (coordd) instead of the whole registry."""
    return (_START_TIME, _CPU, _RSS, _FDS)
