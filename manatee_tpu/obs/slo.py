"""SLO evaluation: error budgets and multi-window multi-burn-rate alerts.

The prober (`daemons/prober.py`) produces a stream of good/bad events
per SLI (a synchronous write acked, a replica read inside its
staleness budget); this module turns that stream into the two numbers
an operator actually pages on:

- **budget remaining** — of the errors the objective allows over its
  rolling window, how much is left;
- **burn rate** — how fast the budget is being consumed right now,
  as a multiple of the all-window-exactly-at-objective rate (burn 1.0
  = the budget lands at zero exactly when the window closes).

Alerting follows the multi-window multi-burn-rate recipe (Google SRE
workbook): a rule fires only when BOTH a long window and a short
window exceed the rule's burn factor — the long window keeps one
transient blip from paging, the short window makes the alert reset
promptly once the incident is over.  Two severities ship by default:
``page`` (fast burn: minutes to empty) and ``ticket`` (slow burn:
hours).  Alert transitions are recorded as journal events
(``slo.alert.fired`` / ``slo.alert.resolved``) and counted in the
registry; the active set is served at ``GET /alerts``
(:func:`alerts_http_reply`) and rendered fleet-wide by
``manatee-adm slo``.

Accounting is O(1) per event: counts land in fixed-width time buckets
in a bounded deque per (SLO, shard) series; evaluation sums at most
``retention / bucket`` buckets on demand (scrape/poll time), never on
the event path.
"""

from __future__ import annotations

import time
from collections import deque

from manatee_tpu.obs.causal import hlc_now
from manatee_tpu.obs.journal import get_journal
from manatee_tpu.obs.metrics import get_registry

_REG = get_registry()
_ALERTS_FIRED = _REG.counter(
    "slo_alerts_total", "SLO burn-rate alert firings",
    ("slo", "severity"))
_EVENTS = _REG.counter(
    "slo_events_total", "good/bad events accounted against SLOs",
    ("slo", "result"))

# severity -> default (long_s, short_s, factor).  Windows are scaled
# for this control plane's drills (seconds-to-minutes incidents), not
# a 30-day production budget — deployments override via config.
DEFAULT_BURN_RULES = {
    "page": {"long_s": 60.0, "short_s": 5.0, "factor": 14.4},
    "ticket": {"long_s": 600.0, "short_s": 60.0, "factor": 3.0},
}

DEFAULT_WINDOW_S = 3600.0
DEFAULT_BUCKET_S = 1.0


class SLOConfigError(ValueError):
    """A malformed SLO definition (config wiring surfaces this)."""


class SLOConfig:
    """One objective: a named SLI with a target ratio over a rolling
    window, plus its burn-rate alert rules."""

    __slots__ = ("name", "description", "objective", "window_s",
                 "burn_rules")

    def __init__(self, name: str, *, objective: float,
                 window_s: float = DEFAULT_WINDOW_S,
                 description: str = "",
                 burn_rules: dict | None = None):
        if not name:
            raise SLOConfigError("SLO needs a name")
        if not (0.0 < objective < 1.0):
            raise SLOConfigError(
                "objective must be in (0, 1), got %r" % (objective,))
        if window_s <= 0:
            raise SLOConfigError("window_s must be > 0")
        self.name = name
        self.description = description
        self.objective = float(objective)
        self.window_s = float(window_s)
        rules = dict(DEFAULT_BURN_RULES) if burn_rules is None \
            else dict(burn_rules)
        for sev, rule in rules.items():
            if not (rule.get("long_s", 0) > rule.get("short_s", 0) > 0):
                raise SLOConfigError(
                    "%s/%s: need long_s > short_s > 0" % (name, sev))
            if rule.get("factor", 0) <= 0:
                raise SLOConfigError(
                    "%s/%s: factor must be > 0" % (name, sev))
        self.burn_rules = rules

    def to_dict(self) -> dict:
        return {"name": self.name, "description": self.description,
                "objective": self.objective, "window_s": self.window_s,
                "burn_rules": self.burn_rules}


def parse_slo_configs(raw) -> list[SLOConfig]:
    """Config-file list -> validated configs (the daemon wiring path).
    Raises :class:`SLOConfigError` on anything malformed — a typo'd
    objective must refuse at boot, not alert wrong forever."""
    out = []
    for ent in raw or ():
        if not isinstance(ent, dict):
            raise SLOConfigError("SLO entry must be an object: %r" % ent)
        kw = {k: ent[k] for k in ("objective", "window_s",
                                  "description", "burn_rules")
              if k in ent}
        try:
            out.append(SLOConfig(ent.get("name", ""), **kw))
        except TypeError as e:
            raise SLOConfigError(str(e)) from None
    names = [c.name for c in out]
    if len(set(names)) != len(names):
        raise SLOConfigError("duplicate SLO names: %r" % names)
    return out


def default_slos() -> list[SLOConfig]:
    """The prober's stock objectives (overridden by its config)."""
    return [
        SLOConfig("write_availability", objective=0.999,
                  description="synchronous writes acked by the "
                              "shard's primary"),
        SLOConfig("read_staleness", objective=0.99,
                  description="replica reads inside the staleness "
                              "budget"),
    ]


class _Series:
    """Good/bad counts for one (SLO, shard), in fixed-width time
    buckets.  The deque is bounded by retention/bucket; recording is
    an O(1) append/increment."""

    __slots__ = ("bucket_s", "retention_s", "_buckets")

    def __init__(self, bucket_s: float, retention_s: float):
        self.bucket_s = bucket_s
        self.retention_s = retention_s
        maxlen = int(retention_s / bucket_s) + 2
        self._buckets: deque[list] = deque(maxlen=maxlen)

    def record(self, now: float, good: int, bad: int) -> None:
        idx = int(now / self.bucket_s)
        if self._buckets and self._buckets[-1][0] == idx:
            b = self._buckets[-1]
            b[1] += good
            b[2] += bad
        else:
            self._buckets.append([idx, good, bad])

    def totals(self, now: float, window_s: float) -> tuple[int, int]:
        """(good, bad) over the trailing *window_s*."""
        lo = int((now - window_s) / self.bucket_s)
        good = bad = 0
        for idx, g, b in reversed(self._buckets):
            if idx <= lo:
                break
            good += g
            bad += b
        return good, bad


class Alert:
    __slots__ = ("slo", "shard", "severity", "factor", "since",
                 "burn_long", "burn_short")

    def __init__(self, slo: str, shard: str, severity: str,
                 factor: float, since: float):
        self.slo = slo
        self.shard = shard
        self.severity = severity
        self.factor = factor
        self.since = since
        self.burn_long = 0.0
        self.burn_short = 0.0

    def to_dict(self) -> dict:
        return {"slo": self.slo, "shard": self.shard,
                "severity": self.severity, "factor": self.factor,
                "since": round(self.since, 3),
                "burn_long": round(self.burn_long, 2),
                "burn_short": round(self.burn_short, 2)}


class SLOEngine:
    """Good/bad accounting + burn-rate evaluation for a set of SLOs,
    per shard.  Event-loop confined like every obs singleton."""

    def __init__(self, configs: list[SLOConfig] | None = None, *,
                 bucket_s: float = DEFAULT_BUCKET_S,
                 clock=time.time):
        self.configs = {c.name: c
                        for c in (configs or default_slos())}
        self.bucket_s = float(bucket_s)
        self._clock = clock
        self._series: dict[tuple[str, str], _Series] = {}
        self._active: dict[tuple[str, str, str], Alert] = {}

    # -- event path (O(1)) --

    def record(self, slo: str, *, good: bool, shard: str = "-",
               n: int = 1) -> None:
        cfg = self.configs.get(slo)
        if cfg is None:
            raise SLOConfigError("unknown SLO %r" % slo)
        key = (slo, shard)
        s = self._series.get(key)
        if s is None:
            retention = max([cfg.window_s]
                            + [r["long_s"]
                               for r in cfg.burn_rules.values()])
            s = _Series(self.bucket_s, retention)
            self._series[key] = s
        s.record(self._clock(),
                 n if good else 0, 0 if good else n)
        _EVENTS.inc(n, slo=slo, result="good" if good else "bad")

    # -- evaluation (poll/scrape path) --

    def _burn(self, s: _Series, cfg: SLOConfig, now: float,
              window_s: float) -> tuple[float, int]:
        good, bad = s.totals(now, window_s)
        total = good + bad
        if total == 0:
            return 0.0, 0
        return (bad / total) / (1.0 - cfg.objective), total

    def evaluate(self) -> list[Alert]:
        """Re-derive the active alert set and journal transitions.
        Returns the alerts active after this pass."""
        now = self._clock()
        journal = get_journal()
        for (slo, shard), s in self._series.items():
            cfg = self.configs[slo]
            for sev, rule in cfg.burn_rules.items():
                burn_long, n_long = self._burn(s, cfg, now,
                                               rule["long_s"])
                burn_short, _n = self._burn(s, cfg, now,
                                            rule["short_s"])
                key = (slo, shard, sev)
                firing = (n_long > 0
                          and burn_long >= rule["factor"]
                          and burn_short >= rule["factor"])
                alert = self._active.get(key)
                if firing:
                    if alert is None:
                        alert = Alert(slo, shard, sev,
                                      rule["factor"], now)
                        self._active[key] = alert
                        _ALERTS_FIRED.inc(slo=slo, severity=sev)
                        journal.record("slo.alert.fired", slo=slo,
                                       shard=shard, severity=sev,
                                       burn_long=round(burn_long, 2),
                                       burn_short=round(burn_short, 2))
                    alert.burn_long = burn_long
                    alert.burn_short = burn_short
                elif alert is not None:
                    del self._active[key]
                    journal.record("slo.alert.resolved", slo=slo,
                                   shard=shard, severity=sev,
                                   after_s=round(now - alert.since, 3))
        return sorted(self._active.values(),
                      key=lambda a: (a.slo, a.shard, a.severity))

    def status(self) -> list[dict]:
        """Per-(SLO, shard) budget accounting over the objective's own
        window — the `manatee-adm slo` table rows."""
        now = self._clock()
        out = []
        for (slo, shard), s in sorted(self._series.items()):
            cfg = self.configs[slo]
            good, bad = s.totals(now, cfg.window_s)
            total = good + bad
            allowed = total * (1.0 - cfg.objective)
            burn, _n = self._burn(s, cfg, now, cfg.window_s)
            out.append({
                "slo": slo,
                "shard": shard,
                "objective": cfg.objective,
                "window_s": cfg.window_s,
                "good": good,
                "bad": bad,
                "ratio": (good / total) if total else None,
                "budget_remaining": ((allowed - bad) / allowed
                                     if allowed > 0 else None),
                "burn": round(burn, 3),
            })
        return out


# ---- process singleton (None until a daemon wires SLOs in) ----

_ENGINE: SLOEngine | None = None


def init_slo_engine(configs: list[SLOConfig] | None = None,
                    **kw) -> SLOEngine:
    global _ENGINE
    _ENGINE = SLOEngine(configs, **kw)
    return _ENGINE


def get_slo_engine() -> SLOEngine | None:
    return _ENGINE


def alerts_http_reply(engine: SLOEngine | None, _query
                      ) -> tuple[dict, int]:
    """The WHOLE ``GET /alerts`` endpoint minus the web framework —
    active burn-rate alerts plus the per-SLO budget table."""
    if engine is None:
        return {"error": "no SLO engine on this daemon (the prober "
                         "evaluates SLOs; see docs/observability.md)"
                }, 404
    alerts = engine.evaluate()
    return {
        "now": round(time.time(), 3),
        "hlc": hlc_now(),
        "alerts": [a.to_dict() for a in alerts],
        "slos": engine.status(),
        "configs": [c.to_dict()
                    for _n, c in sorted(engine.configs.items())],
    }, 200
