"""In-memory ring-buffer event journal (one per process).

Every notable control-plane moment — transition begun/committed, leader
acquired/lost, coord session events, probe state flips, restore
start/finish — is recorded as one small dict.  The ring is fixed-size
(observability must never grow without bound inside an HA daemon) and
exposed verbatim by the status server's ``GET /events``;
``manatee-adm events`` fans out across peers and merges the rings into
the shard timeline.

Event shape::

    {"seq":   int,     # per-process, monotonically increasing
     "ts":    float,   # epoch seconds (wall clock, for cross-peer merge)
     "time":  str,     # ISO-8601 ms UTC of ts
     "hlc":   str,     # hybrid-logical-clock stamp (obs/causal.py)
     "peer":  str,     # this peer's id (set_peer at daemon startup)
     "event": str,     # dotted name, e.g. "transition.committed"
     "trace": str|None,# trace id (bound or explicit)
     ...}              # free-form detail fields
"""

from __future__ import annotations

import time
from collections import deque

from manatee_tpu.obs.causal import hlc_now
from manatee_tpu.obs.trace import current_trace

DEFAULT_CAPACITY = 2048

_RESERVED = frozenset(("seq", "ts", "time", "hlc", "peer", "event",
                       "trace"))


def _iso_ms(ts: float) -> str:
    ms = int(round((ts % 1.0) * 1000))
    sec = int(ts)
    if ms >= 1000:                  # carry: .9995+ rounds into the next second
        sec += 1
        ms -= 1000
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(sec))
    return "%s.%03dZ" % (base, ms)


class EventJournal:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._buf: deque[dict] = deque(maxlen=capacity)
        self._seq = 0
        self.peer: str | None = None

    def record(self, event: str, *, trace_id: str | None = None,
               **fields) -> dict:
        """Append one event.  *trace_id* defaults to the trace bound in
        the current context; detail *fields* may not shadow the core
        keys."""
        self._seq += 1
        ts = round(time.time(), 3)   # one value for ts AND time
        ent = {
            "seq": self._seq,
            "ts": ts,
            "time": _iso_ms(ts),
            "hlc": hlc_now(),
            "peer": self.peer,
            "event": event,
            "trace": trace_id if trace_id is not None else current_trace(),
        }
        for k, v in fields.items():
            if k not in _RESERVED:
                ent[k] = v
        self._buf.append(ent)
        return ent

    def events(self, *, since: int = 0, limit: int | None = None
               ) -> list[dict]:
        """Events with seq > *since*, oldest first, newest *limit*."""
        out = [e for e in self._buf if e["seq"] > since]
        if limit is not None and limit >= 0:
            # NOT out[-limit:]: -0 slices the whole list, so limit=0
            # would return everything instead of nothing
            out = out[-limit:] if limit else []
        return out

    def __len__(self) -> int:
        return len(self._buf)


_JOURNAL = EventJournal()


def get_journal() -> EventJournal:
    """The process-wide journal every component records into."""
    return _JOURNAL


def set_peer(peer_id: str) -> None:
    """Stamp this process's peer identity onto subsequent events (called
    once at daemon wiring time)."""
    _JOURNAL.peer = peer_id
