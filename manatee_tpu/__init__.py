"""manatee_tpu — a clean-room rebuild of the capabilities of
TritonDataCenter/manatee: an automated fault-monitoring, leader-election and
failover control plane for replicated PostgreSQL.

The reference (/root/reference) is Node.js + ZooKeeper + ZFS.  This rebuild is
Python 3 / asyncio with pluggable backends:

- storage:  zfs(8) in production, a directory/hardlink backend for dev images
  without ZFS (``manatee_tpu.storage``);
- coordination: an in-repo coordination service speaking a znode-like data
  model (sessions, ephemeral-sequential nodes, one-shot watches, versioned
  CAS writes, transactions), with an in-memory backend for unit tests
  (``manatee_tpu.coord``);
- database engine: real ``postgres``/``initdb`` binaries when present, and a
  faithful simulated postgres child process for single-host integration
  testing (``manatee_tpu.pg``).

Layer map (mirrors SURVEY.md §1):

    cli / adm            manatee_tpu.cli, manatee_tpu.adm
    daemons              manatee_tpu.daemons.{sitter,backupserver,snapshotter}
    shard orchestration  manatee_tpu.shard
    state machine        manatee_tpu.state.machine   (first-class here; the
                         reference outsources it to the manatee-state-machine
                         git dependency, package.json:31)
    consensus            manatee_tpu.coord.manager   (lib/zookeeperMgr.js)
    database mgmt        manatee_tpu.pg.manager      (lib/postgresMgr.js)
    data plane           manatee_tpu.storage, manatee_tpu.backup
    utilities            manatee_tpu.utils
"""

__version__ = "0.1.0"
