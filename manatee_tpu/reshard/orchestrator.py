"""The resumable reshard step machine behind `manatee-adm reshard`.

One shard's key range is split in place: the source keeps the low
half, a new target shard takes ``[splitKey, hi)`` seeded over the
incremental backup plane.  The step sequence::

    plan -> seed -> catchup -> freeze -> final -> flip -> verify
         -> cleanup -> done

Every arrow is a durable CAS on the step record (plan.py), so a
crashed orchestrator resumes exactly where it died (``--resume``) or
rolls back (``--abort``, any step before ``flip``'s map CAS).  The
shard map is the single ownership authority: no step hands a key
range to two owners, because ownership only ever changes in one
compare-and-set map write.

Mechanics per step:

- **seed / catchup**: ``RestoreClient.restore`` against the source
  primary's backup server — full first, then the PR 9 delta
  negotiation makes every later round incremental (received
  snapshots keep the sender's epoch-ms names, so the negotiation is
  dataset-name-independent).  Each round asks the sender for a fresh
  source snapshot (``freshSnapshot``) so the residual delta shrinks
  toward the write rate.  Rounds repeat until one fits inside the
  cutover budget.
- **freeze**: the source's whole range goes ``frozen`` in the map
  (routers park writes for its keys — park, not error), the source
  shard's topology is frozen against failovers, in-flight router
  writes are drained (confirmed via router /status, or a grace
  sleep), and a marker row is written directly to the source — the
  proxy for "the last acked client write".
- **final**: one more fresh-snapshot delta; it must carry the marker.
- **flip**: the target-shard boot hold (``<shardPath>/reshard-hold``,
  which kept the target's sitters from initializing a database over
  the seed) is released, the target primary is awaited writable, and
  ONE map CAS installs the split: source's low half ``serving``
  (unfreezing it), target's high half ``serving``.  Routers watching
  the map recompile and replay parked writes against the new owner.
- **verify**: canary write/read on both sides plus the freeze
  marker's presence on the target (zero-acked-write-loss evidence).
- **cleanup**: topology unfreeze + the step record marked ``done``.

Failpoints ``reshard.seed`` / ``reshard.delta`` / ``reshard.freeze``
/ ``reshard.flip`` / ``reshard.cleanup`` sit on these seams and join
the crash sweep (tests/test_crash_sweep.py, ``reshard_subproc``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from manatee_tpu import faults
from manatee_tpu.backup.client import RestoreClient
from manatee_tpu.coord.api import (
    BadVersionError,
    NodeExistsError,
    NoNodeError,
    cluster_state_txn,
)
from manatee_tpu.daemons.prober import EngineCache
from manatee_tpu.obs import get_journal, span
from manatee_tpu.reshard.plan import (
    DEFAULT_MAP_PATH,
    DEFAULT_RECORD_PATH,
    FROZEN,
    SERVING,
    ShardMapError,
    ShardMapStore,
    SplitPlan,
    apply_split,
    choose_split_key,
    in_range,
    plan_split,
    range_for_shard,
    with_range_state,
)

log = logging.getLogger("manatee.reshard")

RECORD_FMT = 1
HOLD_NODE = "reshard-hold"

STEPS = ("plan", "seed", "catchup", "freeze", "final", "flip",
         "verify", "cleanup", "done")
# --abort is a rollback only while ownership has not moved: flip's
# map CAS is the point of no return (after it, resume rolls forward)
ABORTABLE = ("plan", "seed", "catchup", "freeze", "final", "aborting")


class ReshardError(Exception):
    """Operator-facing orchestration failure (exit 1, not a crash)."""


def hold_path(shard_path: str) -> str:
    return shard_path.rstrip("/") + "/" + HOLD_NODE


def _now() -> float:
    return time.time()


async def _delta_fault() -> str | None:
    # one call site for the one seam: both the catch-up rounds and
    # the post-freeze final delta are the same incremental-restore
    # seam, so they share the failpoint through this helper
    return await faults.point("reshard.delta")


class Resharder:
    """Drives one split over ONE coordination handle (the process's
    CoordMux session — the orchestrator must not open per-step fresh
    connections).

    *cfg* keys: ``source`` (shard name), ``sourcePath``, ``into``
    (pair, run only), ``splitKey`` (optional — sampled when absent),
    ``target`` (sitter-style config for the target shard's first
    peer: shardPath, dataset, dataDir, ip, storage backend keys),
    ``mapPath``/``recordPath``, ``cutoverBudget`` (seconds a catch-up
    round must fit in before freezing, default 5), ``maxRounds``,
    ``routers`` (status base URLs to confirm the drain against),
    ``freezeGrace``, ``flipTimeout``.
    """

    def __init__(self, coord, cfg: dict, *,
                 storage_factory=None, engine=None):
        self.coord = coord
        self.cfg = cfg
        self.store = ShardMapStore(
            coord,
            map_path=cfg.get("mapPath", DEFAULT_MAP_PATH),
            record_path=cfg.get("recordPath", DEFAULT_RECORD_PATH))
        self.budget = float(cfg.get("cutoverBudget", 5.0))
        self.max_rounds = int(cfg.get("maxRounds", 8))
        self.freeze_grace = float(cfg.get("freezeGrace", 1.0))
        self.flip_timeout = float(cfg.get("flipTimeout", 120.0))
        self.routers = list(cfg.get("routers") or ())
        self.engine = engine or EngineCache()
        # injectable for tests; the default builds the target-side
        # storage from the target config exactly like a sitter would
        self._storage_factory = storage_factory
        self._restore: RestoreClient | None = None
        self.record: dict | None = None
        self._rec_ver = -1

    # ---- plumbing ----

    def _target_cfg(self) -> dict:
        t = self.cfg.get("target")
        if not isinstance(t, dict):
            raise ReshardError("reshard needs a target shard config "
                               "(--target-config)")
        return t

    def _target_storage(self):
        if self._storage_factory is not None:
            return self._storage_factory(self._target_cfg())
        from manatee_tpu.shard import build_storage
        return build_storage(self._target_cfg())

    def _restore_client(self) -> RestoreClient:
        if self._restore is None:
            t = self._target_cfg()
            self._restore = RestoreClient(
                self._target_storage(),
                dataset=t["dataset"],
                mountpoint=t["dataDir"],
                listen_host=t.get("zfsHost", t.get("ip", "127.0.0.1")),
                listen_port=int(t.get("zfsPort", 0)))
        return self._restore

    async def _state(self, shard_path: str) -> tuple[dict | None, int]:
        try:
            raw, ver = await self.coord.get(shard_path + "/state")
        except NoNodeError:
            return None, -1
        return json.loads(raw.decode()), ver

    async def _advance(self, step: str, **extra) -> None:
        assert self.record is not None
        self.record["step"] = step
        self.record["updated"] = _now()
        self.record.update(extra)
        self._rec_ver = await self.store.write_record(
            self.record, self._rec_ver)
        get_journal().record("reshard.step", step=step,
                             op=self.record.get("op"))

    def _plan(self) -> SplitPlan:
        assert self.record is not None
        return SplitPlan.from_dict(self.record["plan"])

    # ---- entry points ----

    async def run(self) -> dict:
        """Fresh start: plan the split, write the durable record, and
        drive it to done.  Returns the final record."""
        rec, ver = await self.store.load_record()
        if rec is not None and rec.get("step") != "done":
            raise ReshardError(
                "a reshard is already recorded (step %r) — finish it "
                "with --resume or --abort" % rec.get("step"))
        plan = await self._make_plan()
        self.record = {
            "fmt": RECORD_FMT,
            "op": "%s->%s,%s" % (plan.source, plan.source, plan.target),
            "step": "plan",
            "plan": plan.to_dict(),
            "rounds": [],
            "frozeTopology": False,
            "created": _now(),
            "updated": _now(),
        }
        # a finished record is history, not a conflict: overwrite it
        # at its version (fresh create otherwise)
        self._rec_ver = await self.store.write_record(self.record, ver)
        get_journal().record("reshard.start", op=self.record["op"],
                             split_key=plan.split_key)
        await self._ensure_hold()
        await self._advance("seed")
        return await self._drive()

    async def resume(self) -> dict:
        """Continue a crashed run from its durable step."""
        rec, ver = await self.store.load_record()
        if rec is None:
            raise ReshardError("no reshard in progress (no record at "
                               "%s)" % self.store.record_path)
        self.record, self._rec_ver = rec, ver
        get_journal().record("reshard.resume", op=rec.get("op"),
                             step=rec.get("step"))
        if rec["step"] == "aborting":
            return await self._finish_abort()
        return await self._drive()

    async def abort(self) -> dict:
        """Roll back a pre-flip reshard: map back to source-serving,
        seeded target dataset destroyed, hold + record removed."""
        rec, ver = await self.store.load_record()
        if rec is None:
            raise ReshardError("no reshard in progress")
        self.record, self._rec_ver = rec, ver
        if rec["step"] not in ABORTABLE:
            raise ReshardError(
                "step %r is past the flip — ownership already moved; "
                "run --resume to roll forward" % rec["step"])
        await self._advance("aborting")
        return await self._finish_abort()

    # ---- the step machine ----

    async def _drive(self) -> dict:
        assert self.record is not None
        handlers = {
            "plan": self._step_plan, "seed": self._step_seed,
            "catchup": self._step_catchup, "freeze": self._step_freeze,
            "final": self._step_final, "flip": self._step_flip,
            "verify": self._step_verify, "cleanup": self._step_cleanup,
        }
        while self.record["step"] != "done":
            step = self.record["step"]
            fn = handlers.get(step)
            if fn is None:
                raise ReshardError("unknown recorded step %r" % step)
            with span("reshard." + step, op=self.record.get("op")):
                await fn()
        return self.record

    async def _make_plan(self) -> SplitPlan:
        m, _ver = await self.store.load()
        source = self.cfg["source"]
        into = self.cfg.get("into")
        if not into or len(into) != 2:
            raise ReshardError("--into a,b is required")
        t = self._target_cfg()
        split_key = self.cfg.get("splitKey")
        if split_key is None:
            split_key = await self._sample_split_key(m, source)
        try:
            return plan_split(m, source, tuple(into), split_key,
                              t["shardPath"])
        except ShardMapError as e:
            raise ReshardError(str(e)) from None

    async def _sample_split_key(self, m: dict, source: str) -> str:
        """Median key of the source's current rows (no --at given)."""
        src = range_for_shard(m, source)
        primary = await self._source_primary(src["shardPath"])
        res = await self.engine.for_url(
            primary["pgUrl"]).query_url(
                primary["pgUrl"], {"op": "select"}, 30.0)
        keys = []
        for row in res.get("rows") or ():
            if isinstance(row, dict) and isinstance(
                    row.get("key"), str):
                keys.append(row["key"])
        try:
            return choose_split_key(keys, src)
        except ShardMapError as e:
            raise ReshardError(str(e)) from None

    async def _source_primary(self, shard_path: str) -> dict:
        st, _ = await self._state(shard_path)
        if not st or not st.get("primary"):
            raise ReshardError("source shard at %s has no declared "
                               "primary" % shard_path)
        return st["primary"]

    async def _ensure_hold(self) -> None:
        """The target-shard boot gate: while this node exists, target
        sitters wait before initializing a database (shard.py), so
        the seed lands on a quiescent dataset."""
        path = hold_path(self._plan().target_path)
        body = json.dumps({"op": self.record["op"],
                           "ts": _now()}).encode()
        try:
            await self.coord.mkdirp(self._plan().target_path)
            await self.coord.create(path, body)
        except NodeExistsError:
            pass

    async def _release_hold(self) -> None:
        try:
            await self.coord.delete(hold_path(self._plan().target_path))
        except NoNodeError:
            pass

    async def _step_plan(self) -> None:
        # run() already recorded the plan; a resume landing here just
        # re-ensures the boot hold and moves on
        await self._ensure_hold()
        await self._advance("seed")

    async def _one_round(self, label: str) -> dict:
        """One restore round against the source primary's backup
        server, fresh source snapshot included; returns the round
        stats that feed the record and the bench artifact."""
        plan = self._plan()
        primary = await self._source_primary(
            self.record["plan"]["sourceRange"]["shardPath"])
        rc = self._restore_client()
        t0 = time.monotonic()
        await rc.restore(primary["backupUrl"],
                         isolate_prefix="reshard",
                         incremental=True, fresh_snapshot=True)
        job = rc.current_job or {}
        round_ = {"label": label, "basis": job.get("basis", "full"),
                  "bytes": int(job.get("completed") or 0),
                  "seconds": round(time.monotonic() - t0, 3),
                  "target": plan.target}
        self.record.setdefault("rounds", []).append(round_)
        get_journal().record("reshard.round", **round_)
        return round_

    async def _step_seed(self) -> None:
        await self._ensure_hold()
        if await faults.point("reshard.seed") == "drop":
            raise ReshardError("seed dropped (fault)")
        await self._one_round("seed")
        await self._advance("catchup")

    async def _step_catchup(self) -> None:
        """Delta rounds until one fits the cutover budget: the round
        duration is the honest proxy for how long the final
        (write-frozen) delta will take."""
        rounds = [r for r in self.record.get("rounds", ())
                  if r["label"] == "catchup"]
        while True:
            if len(rounds) >= self.max_rounds:
                log.warning(
                    "catch-up never fit the %.1fs budget in %d rounds;"
                    " freezing anyway (the final delta bounds the "
                    "window)", self.budget, len(rounds))
                break
            if await _delta_fault() == "drop":
                raise ReshardError("delta round dropped (fault)")
            r = await self._one_round("catchup")
            rounds.append(r)
            self._rec_ver = await self.store.write_record(
                self.record, self._rec_ver)
            if r["seconds"] <= self.budget:
                break
        await self._advance("freeze")

    async def _step_freeze(self) -> None:
        if await faults.point("reshard.freeze") == "drop":
            raise ReshardError("freeze dropped (fault)")
        plan = self._plan()
        # 1. topology freeze: no failover may move the source primary
        # out from under the final delta (idempotent on resume; an
        # operator's pre-existing freeze is respected and kept)
        if not self.record.get("frozeTopology"):
            froze = await self._freeze_topology(
                self.record["plan"]["sourceRange"]["shardPath"])
            self.record["frozeTopology"] = froze
        # 2. map freeze: ONE CAS turns the source range frozen —
        # routers watching the map park its writes from here on
        m, ver = await self.store.load()
        src = range_for_shard(m, plan.source)
        if src["state"] != FROZEN:
            await self.store.cas(
                with_range_state(m, plan.source, FROZEN), ver)
            get_journal().record("reshard.freeze", op=self.record["op"],
                                 epoch=m["epoch"] + 1)
        # 3. drain: writes relayed before a router observed the freeze
        # may still be in flight to the source; they are acked, so the
        # final snapshot must include them
        await self._drain_routers(m["epoch"] + 1)
        # 4. the last-acked-write proxy: a marker the final delta MUST
        # carry to the target (verify asserts it)
        primary = await self._source_primary(
            self.record["plan"]["sourceRange"]["shardPath"])
        marker = {"key": plan.split_key, "reshard_marker":
                  self.record["op"], "ts": _now()}
        await self.engine.for_url(primary["pgUrl"]).query_url(
            primary["pgUrl"], {"op": "insert", "value": marker}, 15.0)
        await self._advance("final", marker=marker)

    async def _freeze_topology(self, shard_path: str) -> bool:
        st, ver = await self._state(shard_path)
        if st is None:
            raise ReshardError("no cluster state at %s" % shard_path)
        if st.get("freeze"):
            return False
        st["freeze"] = {"date": time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "reason": "reshard %s" % self.record["op"]}
        try:
            await self.coord.multi(cluster_state_txn(
                shard_path + "/history", shard_path + "/state",
                st, ver))
        except BadVersionError:
            raise ReshardError("lost a state-update race freezing "
                               "the source topology; resume to retry"
                               ) from None
        return True

    async def _unfreeze_topology(self, shard_path: str) -> None:
        st, ver = await self._state(shard_path)
        if st is None or not st.get("freeze"):
            return
        if "reshard" not in str(st["freeze"].get("reason", "")):
            return          # an operator froze it since: not ours
        st.pop("freeze", None)
        try:
            await self.coord.multi(cluster_state_txn(
                shard_path + "/history", shard_path + "/state",
                st, ver))
        except BadVersionError:
            log.warning("lost the unfreeze race on %s; leaving the "
                        "freeze for `manatee-adm unfreeze`", shard_path)

    async def _drain_routers(self, want_epoch: int) -> None:
        """Wait until every configured router has observed the frozen
        map AND has no write still in flight to the source; without
        router URLs, a grace sleep bounds the same window."""
        if not self.routers:
            await asyncio.sleep(self.freeze_grace)
            return
        import aiohttp
        plan = self._plan()
        deadline = time.monotonic() + max(self.freeze_grace * 10, 15.0)
        async with aiohttp.ClientSession() as http:
            while time.monotonic() < deadline:
                ok = True
                for base in self.routers:
                    try:
                        async with http.get(
                                base.rstrip("/") + "/status",
                                timeout=aiohttp.ClientTimeout(
                                    total=5)) as r:
                            body = await r.json()
                    except (aiohttp.ClientError, OSError,
                            asyncio.TimeoutError):
                        ok = False
                        break
                    mp = body.get("map") or {}
                    sh = (mp.get("shards") or {}).get(plan.source) or {}
                    if int(mp.get("epoch") or -1) < want_epoch \
                            or int(sh.get("inflight_writes") or 0):
                        ok = False
                        break
                if ok:
                    return
                await asyncio.sleep(0.1)
        log.warning("router drain confirmation timed out; proceeding "
                    "after the grace window")
        await asyncio.sleep(self.freeze_grace)

    async def _step_final(self) -> None:
        if await _delta_fault() == "drop":
            raise ReshardError("final delta dropped (fault)")
        await self._one_round("final")
        await self._advance("flip")

    async def _step_flip(self) -> None:
        plan = self._plan()
        # release the boot hold: the target's sitters may now declare
        # a cluster on the seeded dataset
        await self._release_hold()
        target_primary = await self._wait_target_primary()
        if await faults.point("reshard.flip") == "drop":
            raise ReshardError("flip dropped (fault)")
        m, ver = await self.store.load()
        owners = {r["shard"] for r in m["ranges"]}
        if plan.target not in owners:
            # THE cutover: one CAS splits the source range, unfreezes
            # the low half, and hands the high half to the target
            new = apply_split(m, plan, state=SERVING)
            await self.store.cas(new, ver)
            get_journal().record("reshard.flip", op=self.record["op"],
                                 epoch=new["epoch"],
                                 split_key=plan.split_key)
        await self._advance("verify",
                            targetPrimary=target_primary.get("id"))

    async def _wait_target_primary(self) -> dict:
        """The target shard must be writable BEFORE ownership flips,
        or parked writes replay into nothing; the seeded peer must be
        the one that declared (an unseeded peer winning the election
        would serve an empty database)."""
        from manatee_tpu.shard import build_ident
        t = self._target_cfg()
        want_id = build_ident(t)["id"]
        plan = self._plan()
        deadline = time.monotonic() + self.flip_timeout
        while time.monotonic() < deadline:
            st, _ = await self._state(plan.target_path)
            primary = (st or {}).get("primary")
            if primary:
                if primary["id"] != want_id:
                    raise ReshardError(
                        "target shard declared primary %s, not the "
                        "seeded peer %s — an unseeded peer won the "
                        "election; abort and retarget"
                        % (primary["id"], want_id))
                try:
                    res = await self.engine.for_url(
                        primary["pgUrl"]).query_url(
                            primary["pgUrl"],
                            {"op": "insert", "value": {
                                "key": plan.split_key,
                                "reshard_canary": self.record["op"],
                                "side": "target-preflip",
                                "ts": _now()}}, 5.0)
                    if res.get("ok"):
                        return primary
                except asyncio.CancelledError:
                    raise
                except Exception as e:     # noqa: BLE001 — retried
                    log.debug("target not writable yet: %s", e)
            await asyncio.sleep(0.25)
        raise ReshardError(
            "target shard never became writable within %.0fs (are its "
            "sitters running?)" % self.flip_timeout)

    async def _step_verify(self) -> None:
        """Canary write/read on BOTH sides of the split + the freeze
        marker's presence on the target."""
        plan = self._plan()
        m, _ver = await self.store.load()
        src_rng = range_for_shard(m, plan.source)
        tgt_rng = range_for_shard(m, plan.target)
        src_primary = await self._source_primary(src_rng["shardPath"])
        tgt_st, _ = await self._state(plan.target_path)
        tgt_primary = (tgt_st or {}).get("primary")
        if not tgt_primary:
            raise ReshardError("target primary vanished before verify")
        checks = [(src_primary, src_rng["lo"], "source"),
                  (tgt_primary, plan.split_key, "target")]
        for primary, key, side in checks:
            value = {"key": key, "reshard_canary": self.record["op"],
                     "side": side, "ts": _now()}
            eng = self.engine.for_url(primary["pgUrl"])
            res = await eng.query_url(
                primary["pgUrl"], {"op": "insert", "value": value},
                15.0)
            if not res.get("ok"):
                raise ReshardError("canary write on the %s side "
                                   "failed: %r" % (side, res))
            got = await eng.query_url(
                primary["pgUrl"], {"op": "select", "limit": 64}, 15.0)
            rows = got.get("rows") or ()
            if not any(isinstance(r, dict)
                       and r.get("reshard_canary") == self.record["op"]
                       and r.get("side") == side for r in rows):
                raise ReshardError("canary row did not read back on "
                                   "the %s side" % side)
        marker = self.record.get("marker")
        if marker:
            eng = self.engine.for_url(tgt_primary["pgUrl"])
            got = await eng.query_url(
                tgt_primary["pgUrl"], {"op": "select"}, 30.0)
            if not any(isinstance(r, dict)
                       and r.get("reshard_marker") == self.record["op"]
                       for r in got.get("rows") or ()):
                raise ReshardError(
                    "the last-acked-write marker never reached the "
                    "target — the final delta was incomplete")
        # belt: the split the map now serves must be internally sound
        if not in_range(tgt_rng, plan.split_key):
            raise ReshardError("flipped map does not route the split "
                               "key to the target")
        await self._advance("cleanup")

    async def _step_cleanup(self) -> None:
        if await faults.point("reshard.cleanup") == "drop":
            raise ReshardError("cleanup dropped (fault)")
        plan = self._plan()
        if self.record.get("frozeTopology"):
            await self._unfreeze_topology(
                self.record["plan"]["sourceRange"]["shardPath"])
            self.record["frozeTopology"] = False
        await self._release_hold()     # belt: flip already removed it
        moved = sum(r["bytes"] for r in self.record.get("rounds", ()))
        await self._advance(
            "done", finished=_now(),
            stats={"bytesMoved": moved,
                   "rounds": len(self.record.get("rounds", ()))})
        get_journal().record("reshard.done", op=self.record["op"],
                             bytes_moved=moved)

    # ---- abort ----

    async def _finish_abort(self) -> dict:
        """Idempotent rollback: map back to source-serving, seeded
        target dataset destroyed, topology unfrozen, hold + record
        gone.  Safe to re-run from any crash inside itself."""
        plan = self._plan()
        m, ver = await self.store.load()
        owners = {r["shard"] for r in m["ranges"]}
        if plan.target in owners:
            raise ReshardError("map already lists the target as an "
                               "owner — past the flip; --resume "
                               "rolls forward")
        src = range_for_shard(m, plan.source)
        if src["state"] == FROZEN:
            await self.store.cas(
                with_range_state(m, plan.source, SERVING), ver)
        if self.record.get("frozeTopology"):
            await self._unfreeze_topology(
                self.record["plan"]["sourceRange"]["shardPath"])
        t = self._target_cfg()
        storage = self._target_storage()
        if await storage.exists(t["dataset"]):
            if await storage.is_mounted(t["dataset"]):
                await storage.unmount(t["dataset"])
            await storage.destroy(t["dataset"], recursive=True)
        await self._release_hold()
        await self.store.delete_record()
        get_journal().record("reshard.aborted", op=self.record["op"])
        self.record["step"] = "aborted"
        return self.record
