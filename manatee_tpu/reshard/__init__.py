"""Automated live resharding (docs/resharding.md).

``plan.py`` holds the pure split-plan math and the versioned
shard-map record (the single ownership authority, CAS'd in the
coordination store); ``orchestrator.py`` is the resumable step
machine behind ``manatee-adm reshard``.
"""

from manatee_tpu.reshard.plan import (
    DEFAULT_MAP_PATH,
    DEFAULT_RECORD_PATH,
    KEY_MAX,
    KEY_MIN,
    ShardMapError,
    ShardMapStore,
    bootstrap_map,
    owner_of,
    plan_split,
    validate_map,
)

__all__ = [
    "DEFAULT_MAP_PATH",
    "DEFAULT_RECORD_PATH",
    "KEY_MAX",
    "KEY_MIN",
    "ShardMapError",
    "ShardMapStore",
    "bootstrap_map",
    "owner_of",
    "plan_split",
    "validate_map",
]
