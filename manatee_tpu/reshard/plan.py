"""Split-plan math + the versioned shard-map record.

The shard map is ONE versioned JSON node in the coordination store —
the single authority for which shard owns which key range.  Keys are
unicode strings compared lexicographically; ranges are half-open
``[lo, hi)`` with ``lo == ""`` meaning the minimum key and
``hi == None`` meaning +inf.  A valid map partitions the whole key
space: sorted, first ``lo`` is ``""``, last ``hi`` is ``None``, each
range's ``hi`` equals the next range's ``lo`` — no overlap, no gap.
That shape IS the exactly-one-authoritative-owner invariant: every
mutation goes through one compare-and-set on the node version, so a
resharder dying at any seam leaves either the old map or the new map,
never a blend.

Range states: ``serving`` (normal) and ``frozen`` (a cutover in
flight: routers park writes for keys in the range until the flip or
an abort returns it to ``serving``; reads keep serving from the
owner).

Everything in this module except :class:`ShardMapStore` is pure and
synchronous so the planner, the doctor check, the router, and the
tests share one implementation of the range rules.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from manatee_tpu.coord.api import (
    BadVersionError,
    CoordClient,
    NoNodeError,
    NodeExistsError,
)

# sibling of the /manatee/<shard> namespace: a node UNDER /manatee
# would show up in `manatee-adm show`'s shard listing
DEFAULT_MAP_PATH = "/manatee-shardmap"
DEFAULT_RECORD_PATH = "/manatee-shardmap-op"

KEY_MIN = ""      # lo of the first range
KEY_MAX = None    # hi of the last range (+inf)

MAP_FMT = 1

SERVING = "serving"
FROZEN = "frozen"


class ShardMapError(Exception):
    """An invalid map, plan, or CAS conflict (message is operator-facing)."""


def key_lt(a: str, b: str | None) -> bool:
    """``a < b`` under the range ordering (``None`` = +inf)."""
    return b is None or a < b


def in_range(rng: dict, key: str) -> bool:
    return rng["lo"] <= key and key_lt(key, rng["hi"])


def validate_map(m: dict) -> None:
    """Raise ShardMapError unless *m* partitions the key space with
    exactly one owner per range (module docstring)."""
    if not isinstance(m, dict) or m.get("fmt") != MAP_FMT:
        raise ShardMapError("unrecognized shard-map fmt: %r"
                            % (m.get("fmt") if isinstance(m, dict)
                               else m))
    ranges = m.get("ranges")
    if not isinstance(ranges, list) or not ranges:
        raise ShardMapError("shard map has no ranges")
    seen_shards: set[str] = set()
    for i, r in enumerate(ranges):
        for k in ("lo", "shard", "shardPath", "state"):
            if k not in r:
                raise ShardMapError("range %d missing %r" % (i, k))
        if r["state"] not in (SERVING, FROZEN):
            raise ShardMapError("range %d has unknown state %r"
                                % (i, r["state"]))
        if r["shard"] in seen_shards:
            raise ShardMapError("shard %r owns more than one range"
                                % r["shard"])
        seen_shards.add(r["shard"])
    if ranges[0]["lo"] != KEY_MIN:
        raise ShardMapError("first range starts at %r, not the "
                            "minimum key" % ranges[0]["lo"])
    if ranges[-1].get("hi") is not None:
        raise ShardMapError("last range ends at %r, not +inf"
                            % ranges[-1]["hi"])
    for a, b in zip(ranges, ranges[1:]):
        hi = a.get("hi")
        if hi is None or hi != b["lo"]:
            raise ShardMapError(
                "ranges %r and %r do not meet: hi=%r lo=%r (every key "
                "must have exactly one owner)"
                % (a["shard"], b["shard"], hi, b["lo"]))
        if not (a["lo"] < hi):
            raise ShardMapError("range %r is empty: [%r, %r)"
                                % (a["shard"], a["lo"], hi))


def owner_of(m: dict, key: str) -> dict:
    """The range record owning *key* (map assumed valid)."""
    for r in m["ranges"]:
        if in_range(r, key):
            return r
    raise ShardMapError("no range owns key %r" % key)


def range_for_shard(m: dict, shard: str) -> dict:
    for r in m["ranges"]:
        if r["shard"] == shard:
            return r
    raise ShardMapError("shard %r is not in the shard map" % shard)


def bootstrap_map(shard: str, shard_path: str) -> dict:
    """A single-range map: *shard* owns the whole key space."""
    return {"fmt": MAP_FMT, "epoch": 0,
            "ranges": [{"lo": KEY_MIN, "hi": KEY_MAX, "shard": shard,
                        "shardPath": shard_path, "state": SERVING}]}


@dataclass
class SplitPlan:
    """The frozen decision `manatee-adm reshard` executes: split the
    source's range at *split_key*; the source keeps the low half, the
    new *target* shard takes ``[split_key, old_hi)``."""
    source: str
    target: str
    target_path: str
    split_key: str
    source_range: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"source": self.source, "target": self.target,
                "targetPath": self.target_path,
                "splitKey": self.split_key,
                "sourceRange": self.source_range}

    @classmethod
    def from_dict(cls, d: dict) -> "SplitPlan":
        return cls(source=d["source"], target=d["target"],
                   target_path=d["targetPath"],
                   split_key=d["splitKey"],
                   source_range=d.get("sourceRange") or {})


def plan_split(m: dict, source: str, into: tuple[str, str],
               split_key: str, target_path: str) -> SplitPlan:
    """Validate a ``reshard <source> --into a,b`` request against the
    current map.  One of *into* must be the source itself (it keeps
    the low half in place — no data moves for it); the other is the
    new target, which must not already own a range.  *split_key* must
    fall strictly inside the source's range so neither half is
    empty."""
    validate_map(m)
    src = range_for_shard(m, source)
    if src["state"] != SERVING:
        raise ShardMapError(
            "source range is %r — another cutover is in flight "
            "(resume or abort it first)" % src["state"])
    a, b = into
    if a == b:
        raise ShardMapError("--into names the same shard twice: %r" % a)
    if source not in (a, b):
        raise ShardMapError(
            "one of --into must be the source shard %r (it keeps the "
            "low half of its range in place)" % source)
    target = b if a == source else a
    for r in m["ranges"]:
        if r["shard"] == target:
            raise ShardMapError("target shard %r already owns "
                                "[%r, %r)" % (target, r["lo"], r["hi"]))
    if not (src["lo"] < split_key and key_lt(split_key, src["hi"])):
        raise ShardMapError(
            "split key %r is not strictly inside the source range "
            "[%r, %r)" % (split_key, src["lo"], src["hi"]))
    return SplitPlan(source=source, target=target,
                     target_path=target_path, split_key=split_key,
                     source_range=dict(src))


def apply_split(m: dict, plan: SplitPlan, *, state: str) -> dict:
    """The post-flip map: source's range split at the plan's key, the
    high half owned by the target with *state*.  Pure — returns a new
    map with ``epoch`` bumped; the caller CASes it."""
    validate_map(m)
    src = range_for_shard(m, plan.source)
    if not (src["lo"] < plan.split_key
            and key_lt(plan.split_key, src["hi"])):
        raise ShardMapError(
            "split key %r no longer inside source range [%r, %r)"
            % (plan.split_key, src["lo"], src["hi"]))
    out = {"fmt": MAP_FMT, "epoch": int(m["epoch"]) + 1, "ranges": []}
    for r in m["ranges"]:
        if r["shard"] != plan.source:
            out["ranges"].append(dict(r))
            continue
        low = dict(r)
        low["hi"] = plan.split_key
        low["state"] = SERVING
        out["ranges"].append(low)
        out["ranges"].append({
            "lo": plan.split_key, "hi": r.get("hi"),
            "shard": plan.target, "shardPath": plan.target_path,
            "state": state})
    validate_map(out)
    return out


def with_range_state(m: dict, shard: str, state: str) -> dict:
    """A new map with *shard*'s range state replaced, epoch bumped."""
    out = {"fmt": MAP_FMT, "epoch": int(m["epoch"]) + 1,
           "ranges": [dict(r) for r in m["ranges"]]}
    range_for_shard(out, shard)["state"] = state
    validate_map(out)
    return out


def choose_split_key(keys: list[str], rng: dict) -> str:
    """Median in-range key from a sample — the default when the
    operator gives no ``--at``.  Needs at least two distinct in-range
    keys so both halves are nonempty."""
    eligible = sorted({k for k in keys
                       if isinstance(k, str) and in_range(rng, k)
                       and k > rng["lo"]})
    if not eligible:
        raise ShardMapError(
            "cannot choose a split key: no sampled keys fall strictly "
            "inside [%r, %r) — pass --at KEY" % (rng["lo"], rng["hi"]))
    return eligible[len(eligible) // 2]


class ShardMapStore:
    """The shard-map + step-record nodes, read/CAS'd over one coord
    handle (the orchestrator rides the process's CoordMux session)."""

    def __init__(self, coord: CoordClient, *,
                 map_path: str = DEFAULT_MAP_PATH,
                 record_path: str = DEFAULT_RECORD_PATH):
        self.coord = coord
        self.map_path = map_path
        self.record_path = record_path

    # -- shard map --

    async def init(self, shard: str, shard_path: str) -> dict:
        """Create the bootstrap single-range map; error if one exists."""
        m = bootstrap_map(shard, shard_path)
        try:
            await self.coord.create(
                self.map_path, json.dumps(m).encode())
        except NodeExistsError:
            raise ShardMapError(
                "shard map already exists at %s" % self.map_path
            ) from None
        return m

    async def load(self, watch=None) -> tuple[dict, int]:
        """``(map, version)``; the version is the CAS token."""
        try:
            raw, ver = await self.coord.get(self.map_path, watch=watch)
        except NoNodeError:
            raise ShardMapError(
                "no shard map at %s (run `manatee-adm shardmap init` "
                "first)" % self.map_path) from None
        m = json.loads(raw.decode())
        validate_map(m)
        return m, ver

    async def cas(self, m: dict, version: int) -> int:
        """Write *m* iff the node is still at *version*."""
        validate_map(m)
        try:
            return await self.coord.set(
                self.map_path, json.dumps(m).encode(), version)
        except BadVersionError:
            raise ShardMapError(
                "shard map changed underneath this write (version %d "
                "is stale) — re-read and retry" % version) from None

    # -- durable step record (one active reshard at a time) --

    async def load_record(self) -> tuple[dict | None, int]:
        try:
            raw, ver = await self.coord.get(self.record_path)
        except NoNodeError:
            return None, -1
        return json.loads(raw.decode()), ver

    async def write_record(self, rec: dict, version: int) -> int:
        data = json.dumps(rec).encode()
        if version == -1:
            try:
                await self.coord.create(self.record_path, data)
                return 0
            except NodeExistsError:
                raise ShardMapError(
                    "a reshard record already exists at %s — resume "
                    "or abort it" % self.record_path) from None
        try:
            return await self.coord.set(self.record_path, data, version)
        except BadVersionError:
            raise ShardMapError(
                "reshard record changed underneath this orchestrator "
                "(two resharders running?)") from None

    async def delete_record(self, version: int = -1) -> None:
        try:
            await self.coord.delete(self.record_path, version)
        except NoNodeError:
            pass
