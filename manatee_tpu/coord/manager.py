"""ConsensusMgr — the rebuild of lib/zookeeperMgr.js.

Owns all coordination-service interaction for one peer:

- paths under the shard root (lib/zookeeperMgr.js:82-85):
    <root>/election/<id>-NNNNNNNNNN   ephemeral-sequential membership
    <root>/state                      versioned cluster-state node
    <root>/history/<gen>-NNNNNNNNNN   persistent-sequential audit records
- one-shot watches with automatic re-registration (:204-264);
- stale-session dedup: a restarting peer leaves an older ephemeral
  behind, so actives keep only the HIGHEST sequence per peer id,
  sorted by id (parseAndUniqueActives, :168-200);
- activeChange debounced when the id set is unchanged (idListsEqual,
  :267-300);
- putClusterState writes state + history node in one transaction with
  an optimistic version check (:605-630);
- full client teardown/rebuild on session expiry (:488-586).

Events (emitted via registered callbacks, delivered on the event loop):
    'init'               {'active': [...], 'clusterState': {...}|None}
    'activeChange'       [ {id, ...data}, ... ]
    'clusterStateChange' {...}
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Awaitable, Callable

from manatee_tpu import faults
from manatee_tpu.coord.api import (
    ConnectionLossError,
    CoordClient,
    CoordError,
    NoNodeError,
    SessionExpiredError,
    cluster_state_txn,
)
from manatee_tpu.obs import get_journal
from manatee_tpu.utils.aio import cancel_requests
from manatee_tpu.utils.retry import Backoff, backoff_sleep

log = logging.getLogger("manatee.coord")

# cap of the re-register/setup backoff on watch and session errors
# (zookeeperMgr.js:253 hardwires a fixed 5s; here it is the CEILING of
# a jittered exponential schedule so a coordd outage is not followed by
# every peer re-registering in lockstep)
RETRY_DELAY = 5.0


def parse_and_unique_actives(names: list[str]) -> list[dict]:
    """['a-10','b-25','a-5'] -> [{'id':'a','seq':10,'name':'a-10'}, ...]
    keeping only the newest (highest-seq) entry per id, sorted by id."""
    best: dict[str, dict] = {}
    for n in names:
        idx = n.rfind("-")
        if idx <= 0:
            continue
        try:
            seq = int(n[idx + 1:], 10)
        except ValueError:
            continue
        ent = {"id": n[:idx], "seq": seq, "name": n}
        if ent["id"] not in best or seq > best[ent["id"]]["seq"]:
            best[ent["id"]] = ent
    return [best[k] for k in sorted(best)]


def _id_lists_equal(a: list[dict] | None, b: list[dict] | None) -> bool:
    if a is None or b is None:
        return False
    return [x["id"] for x in a] == [x["id"] for x in b]


class ConsensusMgr:
    def __init__(
        self,
        *,
        client_factory: Callable[[], Awaitable[CoordClient]],
        path: str,
        ident: str,
        data: dict,
        anti_entropy_interval: float = 30.0,
    ):
        """*ident* is the peer id (ip:pgPort:backupPort in the reference,
        lib/shard.js:39-54); *data* is the member payload (zoneId, ip,
        pgUrl, backupUrl).

        *anti_entropy_interval*: cadence of a reconciliation pass that
        plain-reads the state and membership regardless of watches, so a
        lost one-shot watch can delay convergence by at most one period
        (0 disables)."""
        self._factory = client_factory
        root = path.rstrip("/")
        self._election_path = root + "/election"
        self._history_path = root + "/history"
        self._state_path = root + "/state"
        self._ident = ident
        self._data = data

        self._client: CoordClient | None = None
        self._inited = False
        # full path of OUR current election ephemeral (create returns
        # the sequenced name); deleted explicitly on close() because a
        # pooled mux handle's close cannot end the shared session
        self._my_election_node: str | None = None
        self._ready = False    # current client fully set up (joined)
        self._closed = False
        self._active: list[dict] = []
        self._cluster_state: dict | None = None
        self._cluster_state_version: int | None = None
        self._listeners: dict[str, list[Callable]] = {}
        self._lock = asyncio.Lock()   # serializes watch handlers
        self._setup_task: asyncio.Task | None = None
        self._generation_of_setup = 0
        self._anti_entropy_interval = anti_entropy_interval
        self._anti_entropy_task: asyncio.Task | None = None
        # live watch-rearm tasks (fire-and-forget otherwise): held so
        # their exceptions are observable and close() can reap them
        self._rearm_tasks: set[asyncio.Task] = set()

    # ---- events ----

    def on(self, event: str, cb: Callable) -> None:
        self._listeners.setdefault(event, []).append(cb)

    def _emit(self, event: str, payload) -> None:
        for cb in self._listeners.get(event, []):
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                cb(payload)
                continue
            loop.call_soon(cb, payload)

    # ---- public accessors (zookeeperMgr getters) ----

    @property
    def active(self) -> list[dict]:
        out = []
        for a in self._active:
            # id + member data (zookeeperMgr active getter, :97-110), plus
            # the election sequence so the state machine can see join order
            c = {"id": a["id"], "seq": a["seq"]}
            c.update(a.get("data") or {})
            out.append(c)
        return out

    @property
    def cluster_state(self) -> dict | None:
        return self._cluster_state

    @property
    def cluster_state_version(self) -> int | None:
        """Version paired with :attr:`cluster_state`; read both in the
        same event-loop step for a consistent snapshot."""
        return self._cluster_state_version

    @property
    def status(self) -> str:
        if self._client is None:
            return "UNINIT"
        if self._closed:
            return "CLOSED"
        return "CONNECTED" if self._client.session_id else "DISCONNECTED"

    # ---- lifecycle ----

    async def start(self) -> None:
        # run the initial setup AS the tracked _setup_task: a session
        # expiry mid-setup fires _schedule_resetup, which must see the
        # live task and no-op — otherwise it spawns a SECOND concurrent
        # setup loop racing this one for self._client, and the loser's
        # stale-generation on_session closure silently ignores later
        # expiries (the peer drops out of coordination until process
        # restart)
        self._setup_task = asyncio.create_task(self._setup_client())
        try:
            await self._setup_task
        except asyncio.CancelledError:
            if self._setup_task.cancelled():
                if cancel_requests(asyncio.current_task()):
                    # BOTH happened: close() cancelled the setup AND
                    # our own caller was cancelled — the caller's
                    # cancel must win, or wait_for's uncancel
                    # bookkeeping is violated and a cancelled task
                    # keeps running down an error path
                    raise
                # only the SETUP was cancelled (a concurrent close()
                # racing startup): re-raising CancelledError would
                # falsely signal cancellation of an uncancelled
                # caller — surface a clean error
                raise ConnectionLossError(
                    "coordination manager closed during startup"
                ) from None
            # our caller was cancelled (e.g. a wait_for timeout treated
            # as startup failure): the retry loop must not run on
            # detached — it would eventually connect and join the
            # election as a ghost peer.  Await the cancelled task so
            # its own cleanup (closing a half-built client) completes
            # before the caller moves on.
            self._setup_task.cancel()
            try:
                await self._setup_task
            except asyncio.CancelledError:
                pass           # the cancel we just requested
            except Exception:
                pass           # teardown is best-effort here
            if self._setup_task.done() \
                    and not self._setup_task.cancelled() \
                    and self._setup_task.exception() is None \
                    and self._client is not None:
                # the setup FINISHED in the same tick the caller was
                # cancelled (cancel() was a no-op on the done task):
                # nothing else will close the built client, and a
                # caller retrying start() after its timeout would
                # spawn a second client/ephemeral for the same ident
                client, self._client = self._client, None
                self._ready = False
                try:
                    await client.close()
                except (CoordError, OSError):
                    pass
            raise
        if self._anti_entropy_interval > 0:
            self._anti_entropy_task = asyncio.create_task(
                self._anti_entropy_loop())

    async def close(self) -> None:
        self._closed = True
        if self._setup_task and not self._setup_task.done():
            # a retry loop sleeping out RETRY_DELAY must not outlive
            # close() and race the client teardown below
            self._setup_task.cancel()
            try:
                await self._setup_task
            except asyncio.CancelledError:
                pass           # the cancel we just requested
            except Exception:
                pass           # retry loop died on its own: moot now
        if self._anti_entropy_task:
            # finish any in-flight pass before tearing the client down,
            # so no callbacks fire after close() returns
            self._anti_entropy_task.cancel()
            try:
                await self._anti_entropy_task
            except asyncio.CancelledError:
                pass
            except Exception:
                pass
        if self._rearm_tasks:
            # sleeping retry-rearms must not outlive close() and fire
            # a watch handler against the torn-down client
            rearms = list(self._rearm_tasks)
            for t in rearms:
                t.cancel()
            await asyncio.gather(*rearms, return_exceptions=True)
        if self._client:
            if self._my_election_node is not None:
                # prompt departure: a private client's close() ends its
                # session and drops this ephemeral implicitly, but a
                # pooled mux handle's close() leaves the SHARED session
                # (and everything it owns) alive for the sibling
                # shards — delete our election entry explicitly so
                # peers see this shard leave NOW, not when the last
                # sibling drains
                try:
                    await self._client.delete(self._my_election_node)
                except (CoordError, OSError):
                    pass
                self._my_election_node = None
            try:
                await self._client.close()
            except (CoordError, OSError):
                # a TCP reset mid-close must not turn a clean daemon
                # shutdown into a crash
                pass

    async def _anti_entropy_loop(self) -> None:
        """Watch loss insurance: periodically reconcile from plain reads
        (no new watches).  _handle_active debounces unchanged id lists
        and _handle_cluster_state dedups by version, so a quiet pass
        emits nothing."""
        while not self._closed:
            await asyncio.sleep(self._anti_entropy_interval)
            client = self._client
            # skip while a session rebuild is in flight: our own
            # election node may not be re-created yet, and reporting
            # membership without ourselves would be false
            if client is None or not self._inited or not self._ready:
                continue
            try:
                async with self._lock:
                    if self._closed or client is not self._client \
                            or not self._ready:
                        continue
                    await self.refresh_cluster_state(client)
                    names = await client.get_children(self._election_path)
                    await self._handle_active(client, names)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("anti-entropy pass failed: %s", e)

    async def _setup_client(self) -> None:
        """(Re)build the client and all coordination state — the analogue of
        setupZkClient + setupData (lib/zookeeperMgr.js:488-586)."""
        self._generation_of_setup += 1
        gen = self._generation_of_setup
        self._ready = False
        bo = Backoff("coord.setup", base=0.5, cap=RETRY_DELAY)
        while not self._closed:
            client = None
            try:
                client = await self._factory()
                self._client = client

                def on_session(ev: str, _gen=gen):
                    if ev == "expired" and not self._closed \
                            and _gen == self._generation_of_setup:
                        log.info("coord session expired; rebuilding client")
                        self._schedule_resetup()

                client.on_session_event(on_session)
                await self._setup_data(client)
                self._ready = True
                return
            except asyncio.CancelledError:
                # a cancelled setup (start() timeout/abandonment, or
                # close()) must not strand a half-built CONNECTED
                # client: its live session would keep a ghost
                # ephemeral in the election until session timeout
                if client is not None:
                    if self._client is client:
                        # don't leave status/consumers pointing at the
                        # closed instance
                        self._client = None
                    try:
                        await client.close()
                    except (CoordError, OSError):
                        pass
                raise
            except (CoordError, OSError) as e:
                # OSError: transient TCP failures (refused, reset, SYN
                # drops under load) must retry, not kill the daemon.
                # Close the half-built client or its still-live session
                # leaves a ghost ephemeral in the election.
                if client is not None:
                    if self._client is client:
                        # status/consumers must not hold the closed
                        # instance for the whole retry window
                        self._client = None
                    try:
                        await client.close()
                    except (CoordError, OSError):
                        pass
                log.warning("coord setup failed (%s); retrying "
                            "(attempt %d)", e, bo.attempts + 1)
                await bo.sleep()

    def _schedule_resetup(self) -> None:
        if self._setup_task and not self._setup_task.done():
            return
        self._ready = False
        self._setup_task = asyncio.create_task(self._setup_client())

    async def _setup_data(self, client: CoordClient) -> None:
        """mkdirp directories, watch state, join election, watch election
        (setupData, lib/zookeeperMgr.js:419-471)."""
        await client.mkdirp(self._election_path)
        await client.mkdirp(self._history_path)
        await self._read_state_and_watch(client)
        # sweep our OWN ghosts before (re)joining: election entries
        # with our ident owned by OUR CURRENT session.  A private
        # client's ghosts (a failed prior setup attempt) die when
        # close() ends its session — but a pooled mux handle shares
        # its session with every other shard in the process, so
        # close() cannot end it and the ghost would outlive every
        # retry.  Scoped to our session id on purpose: a fast-restart
        # predecessor's stale entry rides a DIFFERENT (dying) session
        # and must be left to expire — membership dedupes it
        # (parse_and_unique_actives, MANATEE_206) and tests pin the
        # overlap window.
        sid = getattr(client, "session_id", None)
        if sid is not None:
            for n in await client.get_children(self._election_path):
                if n[:n.rfind("-")] != self._ident:
                    continue
                st = await client.exists(self._election_path + "/" + n)
                if st is None or st.ephemeral_owner != sid:
                    continue
                try:
                    await client.delete(self._election_path + "/" + n)
                except NoNodeError:
                    pass
        self._my_election_node = await client.create(
            self._election_path + "/" + self._ident + "-",
            json.dumps(self._data).encode(),
            ephemeral=True, sequential=True)
        await self._read_active_and_watch(client)
        if not self._inited:
            self._inited = True
            get_journal().record(
                "coord.init", members=[a["id"] for a in self.active])
            self._emit("init", {
                "active": self.active,
                "clusterState": self._cluster_state,
            })
        else:
            # a post-init rebuild (session expiry): membership knowledge
            # was reconstructed from scratch — consumers that reason
            # about "how long has X been absent" must re-arm
            get_journal().record(
                "coord.session.rebuilt",
                members=[a["id"] for a in self.active])
            self._emit("sessionRebuilt", {
                "active": self.active,
                "clusterState": self._cluster_state,
            })

    # ---- state watch ----

    def _make_watch(self, handler: Callable[[CoordClient], Awaitable[None]],
                    client: CoordClient):
        """One-shot watch callback that re-reads and re-registers, retrying
        on errors (watch(), lib/zookeeperMgr.js:204-264)."""

        def fired(_event):
            if self._closed or client is not self._client:
                return

            async def rearm():
                retry = False
                async with self._lock:
                    if self._closed or client is not self._client:
                        return
                    try:
                        await handler(client)
                    except (ConnectionLossError, SessionExpiredError):
                        pass  # session path handles teardown/rebuild
                    except CoordError as e:
                        log.warning("watch handler error on %s: %s; retrying",
                                    handler.__name__, e)
                        retry = True
                if retry:
                    # sleep OUTSIDE the lock: holding it for the delay
                    # would stall every other watch handler (e.g. the
                    # activeChange that kicks a takeover) behind one
                    # failing re-read.  RETRY_DELAY plus up-to-one-
                    # delay of jitter: decorrelated across the shard,
                    # never retrying FASTER than the reference's fixed
                    # schedule against a struggling coordd.
                    await backoff_sleep("coord.watch_rearm",
                                        RETRY_DELAY)
                    fired(None)

            t = asyncio.create_task(rearm())
            self._rearm_tasks.add(t)
            t.add_done_callback(self._rearm_tasks.discard)

        return fired

    async def _read_state_and_watch(self, client: CoordClient) -> None:
        handler = self._read_state_and_watch_inner
        watch_cb = self._make_watch(handler, client)
        try:
            data, version = await client.get(self._state_path, watch=watch_cb)
        except NoNodeError:
            # not created yet: watch for its creation via exists; if it was
            # created while we looked away, plain-read it (the watch is
            # already armed — zookeeperMgr.js:227-236)
            stat = await client.exists(self._state_path, watch=watch_cb)
            if stat is not None:
                data, version = await client.get(self._state_path)
            else:
                return
        self._handle_cluster_state(data, version)

    async def _read_state_and_watch_inner(self, client: CoordClient) -> None:
        await self._read_state_and_watch(client)

    def _handle_cluster_state(self, data: bytes, version: int) -> None:
        try:
            state = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            log.error("unparseable cluster state at v%s", version)
            return
        changed = version != self._cluster_state_version
        self._cluster_state = state
        self._cluster_state_version = version
        if self._inited and changed:
            # the observed transition carries its initiator's trace id
            # (state/machine.py embeds it at write time): journal under
            # it so every peer's reaction lines up in the shard timeline
            get_journal().record(
                "clusterstate.change",
                trace_id=state.get("trace") if isinstance(state, dict)
                else None,
                generation=(state or {}).get("generation"),
                version=version)
            self._emit("clusterStateChange", state)

    # ---- active watch ----

    async def _read_active_and_watch(self, client: CoordClient) -> None:
        handler = self._read_active_and_watch_inner
        watch_cb = self._make_watch(handler, client)
        names = await client.get_children(self._election_path, watch=watch_cb)
        await self._handle_active(client, names)

    async def _read_active_and_watch_inner(self, client: CoordClient) -> None:
        await self._read_active_and_watch(client)

    async def _handle_active(self, client: CoordClient,
                             names: list[str]) -> None:
        """Dedup, fetch member data (with id+seq cache), debounce, emit
        (handleActive, lib/zookeeperMgr.js:307-386)."""
        active = parse_and_unique_actives(names)
        cache = {a["id"]: a for a in self._active}
        for ent in active:
            cached = cache.get(ent["id"])
            if cached and cached["seq"] == ent["seq"]:
                ent["data"] = cached.get("data")
                continue
            try:
                data, _v = await client.get(
                    self._election_path + "/" + ent["name"])
                ent["data"] = json.loads(data.decode())
            except NoNodeError:
                ent["data"] = {}
            except (ValueError, UnicodeDecodeError):
                ent["data"] = {}
        should_debounce = _id_lists_equal(self._active, active)
        self._active = active
        if self._inited and not should_debounce:
            get_journal().record(
                "membership.change",
                members=[a["id"] for a in active])
            self._emit("activeChange", self.active)

    async def refresh_cluster_state(self, client: CoordClient | None = None
                                    ) -> None:
        """Force a plain re-read of the state node (no new watch).  The
        self-healing path for a lost watch: callers that observe a CAS
        conflict call this so a stale cache cannot persist."""
        client = client if client is not None else self._client
        if client is None:
            return
        try:
            data, version = await client.get(self._state_path)
        except CoordError:
            return
        self._handle_cluster_state(data, version)

    # ---- putClusterState ----

    async def put_cluster_state(self, state: dict, *,
                                expected_version: int | None = None
                                ) -> None:
        """Write state + history atomically with optimistic versioning
        (putClusterState, lib/zookeeperMgr.js:605-630).  Raises
        BadVersionError on CAS conflict.  Pass *expected_version* (from
        :attr:`cluster_state_version` at snapshot time) so a decision
        computed from an older state cannot silently overwrite writes
        that landed mid-decision."""
        if self._client is None:
            raise ConnectionLossError("not connected")
        if "generation" not in state:
            raise CoordError("cluster state requires a generation")
        # the durable-write seam: error/delay/stall here models a
        # coordination service that stops accepting (or slows) the one
        # write HA correctness rides on
        await faults.point("coord.put_state")
        version = (expected_version if expected_version is not None
                   else self._cluster_state_version)
        res = await self._client.multi(cluster_state_txn(
            self._history_path, self._state_path, state, version))
        self._cluster_state = state
        # the set op reports the new version; a fresh create starts at 0
        self._cluster_state_version = res[1] if isinstance(res[1], int) else 0
