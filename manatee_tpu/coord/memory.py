"""In-process coordination backend for unit tests and simulation.

A :class:`CoordSpace` is the moral equivalent of a ZooKeeper ensemble:
it owns one znode tree.  Each :class:`MemoryCoord` client gets its own
session; tests drive failure scenarios by expiring sessions
(``space.expire(client)``) exactly where the real system would see a
dead peer's ephemeral nodes vanish.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from manatee_tpu.coord import model
from manatee_tpu.coord.api import (
    CoordClient,
    Op,
    SessionExpiredError,
    Stat,
    WatchCb,
)


class CoordSpace:
    def __init__(self):
        self.tree = model.ZNodeTree()

    def client(self, session_timeout: float = 60.0) -> "MemoryCoord":
        return MemoryCoord(self, session_timeout)

    def expire(self, client: "MemoryCoord") -> None:
        """Simulate session expiry for *client* (peer death as seen by the
        ensemble)."""
        client._expire()


class MemoryCoord(CoordClient):
    def __init__(self, space: CoordSpace, session_timeout: float):
        self._space = space
        self._timeout = session_timeout
        self._session: model.Session | None = None
        self._session_cbs: list[Callable[[str], None]] = []
        self._loop: asyncio.AbstractEventLoop | None = None

    # ---- lifecycle ----

    async def connect(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._session = self._space.tree.create_session(self._timeout)
        self._notify("connected")

    async def close(self) -> None:
        # closing a ZK session removes its ephemerals immediately
        if self._session and not self._session.expired:
            self._space.tree.remove_watches_for(
                lambda w: getattr(w, "__owner__", None) is self)
            self._space.tree.expire_session(self._session.id)

    @property
    def session_id(self) -> str | None:
        return self._session.id if self._session else None

    def on_session_event(self, cb: Callable[[str], None]) -> None:
        self._session_cbs.append(cb)

    def _notify(self, event: str) -> None:
        for cb in list(self._session_cbs):
            self._call_soon(cb, event)

    def _call_soon(self, cb, *args) -> None:
        loop = self._loop or asyncio.get_event_loop()
        loop.call_soon(cb, *args)

    def _expire(self) -> None:
        if self._session and not self._session.expired:
            # drop this client's own watches first: a session does not
            # observe its own ephemerals vanishing (it is dead)
            self._space.tree.remove_watches_for(
                lambda w: getattr(w, "__owner__", None) is self)
            self._space.tree.expire_session(self._session.id)
            self._notify("expired")

    def _check(self) -> None:
        if not self._session:
            raise SessionExpiredError("not connected")
        if self._session.expired:
            raise SessionExpiredError(self._session.id)
        self._space.tree.touch_session(self._session.id)

    def _wrap_watch(self, watch: WatchCb | None):
        if watch is None:
            return None

        def sink(event):
            # deliver asynchronously, and only while our session lives
            if self._session and not self._session.expired:
                self._call_soon(watch, event)

        sink.__owner__ = self
        return sink

    # ---- ops ----

    async def create(self, path: str, data: bytes = b"", *,
                     ephemeral: bool = False,
                     sequential: bool = False) -> str:
        self._check()
        return self._space.tree.create(
            path, data,
            ephemeral_owner=self._session.id if ephemeral else None,
            sequential=sequential)

    async def get(self, path: str, watch: WatchCb | None = None
                  ) -> tuple[bytes, int]:
        self._check()
        data, version = self._space.tree.get(path)
        if watch:
            self._space.tree.add_watch(model.DATA, path, self._wrap_watch(watch))
        return data, version

    async def set(self, path: str, data: bytes, version: int = -1) -> int:
        self._check()
        return self._space.tree.set(path, data, version)

    async def delete(self, path: str, version: int = -1) -> None:
        self._check()
        self._space.tree.delete(path, version)

    async def exists(self, path: str, watch: WatchCb | None = None
                     ) -> Stat | None:
        self._check()
        stat = self._space.tree.exists(path)
        if watch:
            self._space.tree.add_watch(model.DATA, path, self._wrap_watch(watch))
        return stat

    async def get_children(self, path: str, watch: WatchCb | None = None
                           ) -> list[str]:
        self._check()
        children = self._space.tree.get_children(path)
        if watch:
            self._space.tree.add_watch(
                model.CHILDREN, path, self._wrap_watch(watch))
        return children

    async def multi(self, ops: list[Op]) -> list:
        self._check()
        return self._space.tree.multi(ops, session_id=self._session.id)
