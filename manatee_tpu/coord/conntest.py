"""Coordination-service connectivity smoke test.

Reference parity: bin/zkConnTest.js — standalone check that a
coordination address is reachable and serving (create/read/delete a
scratch node), for use from provisioning scripts.

Usage: python -m manatee_tpu.coord.conntest HOST:PORT
"""

from __future__ import annotations

import asyncio
import itertools
import os
import sys
import uuid

from manatee_tpu.coord.api import NodeExistsError
from manatee_tpu.coord.client import NetCoord

# pid + per-process counter + a random component: the old
# epoch-millisecond name collided whenever two probes (a provisioning
# script fanning out) landed in the same millisecond, and pid alone
# still collides across pid namespaces (two containers both probing as
# pid 1).  The random suffix makes the path unique for the probe's
# whole lifetime, so no probe can delete another's scratch node.
_probe_seq = itertools.count(1)


async def conntest(addr: str, timeout: float = 10.0) -> None:
    host, _, port = addr.partition(":")
    client = NetCoord(host, int(port or 2281), session_timeout=10)
    await asyncio.wait_for(client.connect(), timeout)
    path = "/conntest-%d-%d-%s" % (os.getpid(), next(_probe_seq),
                                   uuid.uuid4().hex[:8])
    try:
        await client.create(path, b"ping", ephemeral=True)
    except NodeExistsError:
        pass
    data, _ = await client.get(path)
    assert data == b"ping"
    await client.delete(path)
    await client.close()


def main(argv: list[str] | None = None) -> None:
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1:
        print("usage: conntest HOST:PORT", file=sys.stderr)
        sys.exit(2)
    try:
        asyncio.run(conntest(args[0]))
    except Exception as e:
        print("FAIL: %s" % e, file=sys.stderr)
        sys.exit(1)
    print("OK: %s is serving" % args[0])


if __name__ == "__main__":
    main()
