"""Coordination layer (reference: lib/zookeeperMgr.js + ZooKeeper).

The reference delegates consensus/membership to a ZooKeeper ensemble.  This
rebuild keeps the same data model — a tree of versioned znodes with
ephemeral-sequential nodes, one-shot watches, and transactions — behind a
narrow client API (:mod:`manatee_tpu.coord.api`) with three backends:

- :class:`manatee_tpu.coord.memory.MemoryCoord` — in-process, for unit
  tests and simulation (sessions expired programmatically);
- ``coordd`` (:mod:`manatee_tpu.coord.server`) + the TCP client
  (:mod:`manatee_tpu.coord.client`) — a real service with real session
  timeouts, so multi-process clusters get ZooKeeper-like liveness
  detection on machines without ZooKeeper;
- a ZooKeeper backend can be slotted in later (kazoo/aiozk) without
  touching anything above the API.

:class:`manatee_tpu.coord.manager.ConsensusMgr` reimplements the
zookeeperMgr contract on top: election join, active-list dedup/debounce,
cluster-state watch, and transactional putClusterState with CAS.
"""

from manatee_tpu.coord.api import (
    BadVersionError,
    ConnectionLossError,
    CoordClient,
    CoordError,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    Op,
    SessionExpiredError,
    WatchEvent,
)
from manatee_tpu.coord.memory import CoordSpace, MemoryCoord
from manatee_tpu.coord.manager import ConsensusMgr

__all__ = [
    "BadVersionError",
    "ConnectionLossError",
    "CoordClient",
    "CoordError",
    "NodeExistsError",
    "NoNodeError",
    "NotEmptyError",
    "Op",
    "SessionExpiredError",
    "WatchEvent",
    "CoordSpace",
    "MemoryCoord",
    "ConsensusMgr",
]
