"""The znode tree: single-threaded core shared by the in-memory backend
and the coordd server.

All mutation goes through this class; watch callbacks are invoked
synchronously after a successful mutation (callers deliver them to the
right place — the memory backend schedules them on the event loop, coordd
pushes them down client connections).  Watches are ONE-SHOT, like
ZooKeeper's: triggering removes them.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from manatee_tpu.coord.api import (
    BadVersionError,
    CoordError,
    EventType,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    Op,
    Stat,
    WatchEvent,
    validate_path,
)


@dataclass
class _Node:
    data: bytes = b""
    version: int = 0
    ephemeral_owner: str | None = None
    seq_counter: int = 0
    ctime: float = field(default_factory=time.time)
    children: dict[str, "_Node"] = field(default_factory=dict)


# watch kinds
DATA = "data"      # fires on data change / delete / create (set via get/exists)
CHILDREN = "children"

# (kind, path) -> list of callbacks
WatchSink = Callable[[WatchEvent], None]


@dataclass
class Session:
    id: str
    timeout: float                 # seconds
    last_seen: float = field(default_factory=time.monotonic)
    connected: bool = True
    expired: bool = False
    # Fast crash detection (opt-in): once the session's TCP connection
    # has dropped, it may expire after this much silence instead of the
    # full timeout.  A SIGKILLed peer's kernel sends FIN immediately, so
    # the cluster can fail over in disconnect_grace rather than
    # session_timeout — something ZooKeeper cannot distinguish (it treats
    # disconnect and silence identically).  A partitioned-but-alive peer
    # produces no FIN and still gets the full timeout.
    disconnect_grace: float | None = None
    disconnected_at: float | None = None

    def deadline(self) -> float:
        d = self.last_seen + self.timeout
        if (not self.connected and self.disconnect_grace is not None
                and self.disconnected_at is not None):
            d = min(d, self.disconnected_at + self.disconnect_grace)
        return d


class ZNodeTree:
    def __init__(self):
        self._root = _Node()
        self._watches: dict[tuple[str, str], list[WatchSink]] = {}
        self.sessions: dict[str, Session] = {}
        self._session_counter = 0
        self.on_mutate: Callable[[], None] | None = None
        # node count (incl. root), maintained incrementally on
        # create/delete so a /metrics scrape never walks the tree —
        # scrape cost must not scale with tree size
        self.node_count = 1

    def _mutated(self) -> None:
        if self.on_mutate is not None:
            self.on_mutate()

    # ---- persistence (ZooKeeper-parity durability for coordd) ----

    def to_snapshot(self) -> dict:
        """Serializable view of the PERSISTENT tree.  Ephemerals are
        dropped: after a server restart their sessions are gone, which
        matches clients observing session expiry and re-registering."""
        import base64

        def walk(node: _Node) -> dict:
            return {
                "data": base64.b64encode(node.data).decode(),
                "version": node.version,
                "seq": node.seq_counter,
                "ctime": node.ctime,
                "children": {
                    name: walk(child)
                    for name, child in node.children.items()
                    if child.ephemeral_owner is None
                },
            }

        return {"v": 1, "root": walk(self._root)}

    @classmethod
    def from_snapshot(cls, snap: dict) -> "ZNodeTree":
        import base64

        def build(d: dict) -> _Node:
            node = _Node(
                data=base64.b64decode(d.get("data", "")),
                version=int(d.get("version", 0)),
                ctime=float(d.get("ctime", 0.0)) or time.time(),
            )
            node.seq_counter = int(d.get("seq", 0))
            node.children = {name: build(c)
                             for name, c in d.get("children", {}).items()}
            return node

        tree = cls()
        if snap.get("v") == 1 and "root" in snap:
            tree._root = build(snap["root"])

            def count(node: _Node) -> int:
                return 1 + sum(count(c) for c in node.children.values())

            # one load-time walk seeds the incremental counter; every
            # later mutation maintains it in O(1)
            tree.node_count = count(tree._root)
        return tree

    # ---- sessions ----

    def create_session(self, timeout: float,
                       disconnect_grace: float | None = None) -> Session:
        self._session_counter += 1
        sid = "s%08x-%04d" % (int(time.time()) & 0xFFFFFFFF, self._session_counter)
        s = Session(id=sid, timeout=timeout,
                    disconnect_grace=disconnect_grace)
        self.sessions[sid] = s
        return s

    def touch_session(self, sid: str) -> None:
        s = self.sessions.get(sid)
        if s and not s.expired:
            s.last_seen = time.monotonic()

    def expire_session(self, sid: str) -> None:
        """Remove the session and all its ephemeral nodes (firing watches)."""
        s = self.sessions.get(sid)
        if not s or s.expired:
            return
        s.expired = True
        s.connected = False
        for path in self._ephemerals_of(sid):
            try:
                self.delete(path, -1, force_ephemeral=True)
            except CoordError:
                pass

    def expired_sessions(self, now: float | None = None) -> list[str]:
        """Sessions past their deadline — including CONNECTED ones.

        A hung-but-connected peer (SIGSTOP, stalled host, partition with
        no RST) stops pinging but keeps its TCP socket; ZooKeeper expires
        such sessions on heartbeat silence, and so must we, or the
        cluster never fails over around a wedged peer.  Live clients ping
        at timeout/3 (client.py _ping_loop), which refreshes last_seen.
        """
        now = time.monotonic() if now is None else now
        return [sid for sid, s in self.sessions.items()
                if not s.expired and s.deadline() <= now]

    def _ephemerals_of(self, sid: str) -> list[str]:
        out: list[str] = []

        def walk(node: _Node, path: str):
            for name, child in node.children.items():
                cpath = (path if path != "/" else "") + "/" + name
                if child.ephemeral_owner == sid:
                    out.append(cpath)
                walk(child, cpath)

        walk(self._root, "/")
        return out

    # ---- watches ----

    def add_watch(self, kind: str, path: str, sink: WatchSink) -> None:
        self._watches.setdefault((kind, path), []).append(sink)

    def remove_watches_for(self, predicate: Callable[[WatchSink], bool]) -> None:
        for key in list(self._watches):
            self._watches[key] = [w for w in self._watches[key]
                                  if not predicate(w)]
            if not self._watches[key]:
                del self._watches[key]

    def _fire(self, kind: str, path: str, event: WatchEvent) -> None:
        sinks = self._watches.pop((kind, path), [])
        for sink in sinks:
            try:
                sink(event)
            except Exception:
                pass

    # ---- tree navigation ----

    def _resolve(self, path: str) -> _Node:
        node = self._root
        for comp in [c for c in path.split("/") if c]:
            if comp not in node.children:
                raise NoNodeError(path)
            node = node.children[comp]
        return node

    def _parent_of(self, path: str) -> tuple[_Node, str]:
        validate_path(path)
        if path == "/":
            raise CoordError("cannot operate on /")
        parent_path, _, name = path.rpartition("/")
        parent = self._resolve(parent_path or "/")
        return parent, name

    # ---- ops ----

    def create(self, path: str, data: bytes = b"", *,
               ephemeral_owner: str | None = None,
               sequential: bool = False) -> str:
        parent, name = self._parent_of(path)
        if parent.ephemeral_owner is not None:
            # ZK forbids children under ephemeral nodes; allowing them
            # would let an ephemeral dodge deletion at session expiry
            raise CoordError("ephemeral nodes cannot have children: %s"
                             % path)
        parent_path = path.rpartition("/")[0] or "/"
        if sequential:
            name = "%s%010d" % (name, parent.seq_counter)
            parent.seq_counter += 1
            path = (parent_path if parent_path != "/" else "") + "/" + name
        if name in parent.children:
            raise NodeExistsError(path)
        parent.children[name] = _Node(
            data=bytes(data), ephemeral_owner=ephemeral_owner)
        self.node_count += 1
        self._mutated()
        self._fire(DATA, path, WatchEvent(EventType.CREATED, path))
        self._fire(CHILDREN, parent_path,
                   WatchEvent(EventType.CHILDREN_CHANGED, parent_path))
        return path

    def get(self, path: str) -> tuple[bytes, int]:
        validate_path(path)
        node = self._resolve(path)
        return node.data, node.version

    def set(self, path: str, data: bytes, version: int = -1) -> int:
        validate_path(path)
        node = self._resolve(path)
        if version != -1 and node.version != version:
            raise BadVersionError("%s: expected v%d, have v%d"
                                  % (path, version, node.version))
        node.data = bytes(data)
        node.version += 1
        self._mutated()
        self._fire(DATA, path, WatchEvent(EventType.DATA_CHANGED, path))
        return node.version

    def delete(self, path: str, version: int = -1, *,
               force_ephemeral: bool = False) -> None:
        parent, name = self._parent_of(path)
        if name not in parent.children:
            raise NoNodeError(path)
        node = parent.children[name]
        if version != -1 and node.version != version:
            raise BadVersionError(path)
        if node.children:
            if not force_ephemeral:
                raise NotEmptyError(path)
            # ephemeral nodes cannot have children in ZK; defensive only
            raise NotEmptyError(path)
        del parent.children[name]
        self.node_count -= 1
        self._mutated()
        parent_path = path.rpartition("/")[0] or "/"
        self._fire(DATA, path, WatchEvent(EventType.DELETED, path))
        self._fire(CHILDREN, parent_path,
                   WatchEvent(EventType.CHILDREN_CHANGED, parent_path))

    def exists(self, path: str) -> Stat | None:
        validate_path(path)
        try:
            node = self._resolve(path)
        except NoNodeError:
            return None
        return Stat(version=node.version,
                    ephemeral_owner=node.ephemeral_owner,
                    num_children=len(node.children),
                    ctime=node.ctime)

    def get_children(self, path: str) -> list[str]:
        validate_path(path)
        node = self._resolve(path)
        return sorted(node.children.keys())

    # ---- transactions ----

    def multi(self, ops: list[Op], *, session_id: str | None = None) -> list:
        """Atomic: validate everything would succeed, then apply.  Mirrors
        the ZK transaction used by putClusterState
        (lib/zookeeperMgr.js:605-630)."""
        # Validate against a virtual view: track created/deleted paths and
        # version bumps without mutating the tree.
        virtual_exists: dict[str, bool] = {}
        virtual_version: dict[str, int] = {}

        def v_exists(path: str) -> bool:
            if path in virtual_exists:
                return virtual_exists[path]
            return self.exists(path) is not None

        def v_version(path: str) -> int:
            if path in virtual_version:
                return virtual_version[path]
            node = self._resolve(path)
            return node.version

        for op in ops:
            validate_path(op.path)
            if op.kind == "create":
                parent = op.path.rpartition("/")[0] or "/"
                if not v_exists(parent):
                    raise NoNodeError(parent)
                if not op.sequential and v_exists(op.path):
                    raise NodeExistsError(op.path)
                if not op.sequential:
                    virtual_exists[op.path] = True
                    virtual_version[op.path] = 0
            elif op.kind in ("set", "check"):
                if not v_exists(op.path):
                    raise NoNodeError(op.path)
                if op.version != -1 and v_version(op.path) != op.version:
                    raise BadVersionError(op.path)
                if op.kind == "set":
                    virtual_version[op.path] = v_version(op.path) + 1
            elif op.kind == "delete":
                if not v_exists(op.path):
                    raise NoNodeError(op.path)
                if op.version != -1 and v_version(op.path) != op.version:
                    raise BadVersionError(op.path)
                stat = self.exists(op.path)
                real_children = (set(self.get_children(op.path))
                                 if stat is not None else set())
                prefix = op.path + "/"
                for vpath, vexists in virtual_exists.items():
                    if vpath.startswith(prefix) \
                            and "/" not in vpath[len(prefix):]:
                        name = vpath[len(prefix):]
                        (real_children.add if vexists
                         else real_children.discard)(name)
                if real_children:
                    raise NotEmptyError(op.path)
                virtual_exists[op.path] = False
            else:
                raise CoordError("bad op kind: %r" % op.kind)

        # Apply for real.
        results: list = []
        for op in ops:
            if op.kind == "create":
                results.append(self.create(
                    op.path, op.data or b"",
                    ephemeral_owner=session_id if op.ephemeral else None,
                    sequential=op.sequential))
            elif op.kind == "set":
                results.append(self.set(op.path, op.data or b"", op.version))
            elif op.kind == "delete":
                self.delete(op.path, op.version)
                results.append(None)
            elif op.kind == "check":
                results.append(None)
        return results
