"""coordd — the coordination service daemon.

Plays the role ZooKeeper plays for the reference: znode tree with
versioned CAS writes, ephemeral-sequential nodes, one-shot watches,
transactions, and session-timeout liveness (a SIGKILLed peer's ephemeral
nodes vanish only after its session times out, which is exactly the
failure-detection path of SURVEY.md §5.3).

Wire protocol: newline-delimited JSON over TCP.

  client -> server   {"xid": 1, "op": "create", "path": "/a", "data": "<b64>",
                      "ephemeral": true, "sequential": true}
  server -> client   {"xid": 1, "ok": true, "result": "/a0000000001"}
                     {"xid": 1, "ok": false, "error": "NoNodeError", "msg": "..."}
  watch push         {"watch": {"kind": "data", "type": "deleted", "path": "/a"}}

Sessions: ``hello`` creates (or resumes) a session; a dropped TCP
connection leaves the session alive until ``session_timeout`` elapses —
unless the client opted into a ``disconnect_grace``, in which case a
*disconnected* session expires after that (shorter) grace.  The grace
is the fast crash-detection path: a SIGKILLed peer's kernel FINs its
socket immediately, so coordd can distinguish "process died" (FIN, then
silence) from "process wedged or partitioned" (no FIN; full heartbeat
timeout applies).  ZooKeeper cannot make this distinction — its clients
talk through a session abstraction that deliberately hides connection
state.  ``goodbye`` ends a session explicitly (ephemeral nodes vanish
at once), matching ZooKeeper handle close.

Ensemble mode (--ensemble/--ensemble-id) replicates coordd the way the
reference assumes a ZooKeeper ensemble (etc/sitter.json zkCfg.connStr):

- exactly one member is *leader* and accepts client sessions; followers
  refuse hello with NotLeaderError + a leader hint, and clients rotate
  (NetCoord multi-address).
- the leader ships the persistent tree (snapshot + monotonic seq) to
  followers on every mutation and awaits their acks; with >=3 members
  mutations additionally require a connected majority (no-quorum
  refusal), so a partitioned minority leader cannot diverge the state.
- leadership: highest (seq, lowest id) among a contacted QUORUM wins —
  a follower promotes itself only after reaching a majority of members
  and outranking all of them for promote_grace, so a laggard cut off
  from the up-to-date members can never roll back a majority-acked
  write (the same two-quorums-intersect argument ZooKeeper elections
  rest on).  A returning member always joins an incumbent leader
  instead of reclaiming (leader stickiness).  Dual leaders after a
  partition heal resolve by (seq, lowest id).
- ephemerals/sessions are deliberately NOT replicated: on failover
  clients observe session loss and re-register — the same contract as
  a coordd restart, and the recovery path ConsensusMgr already owns.

This is op-shipping primary/backup, not ZAB/Raft: it needs the quorum
rule above for safety and trades some availability (a two-member
ensemble cannot survive a partition safely).  A follower attaches with
one full-snapshot resync (sync_hello), then receives each persistent
mutation as the op itself — O(op), independent of tree/history size —
and applies it in sequence, acking the seq.  Any gap, version
conflict, or result mismatch on apply means divergence and triggers a
fresh full resync; ephemeral-only mutations (election joins) touch no
persistent state and are not shipped at all.  The CoordClient
interface stays narrow so a real ZK ensemble could back production via
an adapter.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import logging
import signal
import time

from manatee_tpu.coord import model
from manatee_tpu.coord.api import (
    RECONNECT_DELAY,
    BadVersionError,
    CoordError,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    NotLeaderError,
    Op,
)
from manatee_tpu.utils.logutil import setup_logging

log = logging.getLogger("manatee.coordd")

_ERR_NAMES = {
    NoNodeError: "NoNodeError",
    NodeExistsError: "NodeExistsError",
    BadVersionError: "BadVersionError",
    NotEmptyError: "NotEmptyError",
}

MAX_LINE = 8 * 1024 * 1024
# per-connection outbound buffer cap; beyond this the subscriber is
# considered stalled and its connection is aborted (ADVICE r1)
MAX_BUFFERED = 16 * 1024 * 1024
# floor for client-requested disconnect_grace: must outlive the
# client's reconnect delay (plus connect/hello slack) or a transient
# TCP drop expires the session before the first resume attempt can
# happen.  Derived from the shared api constant so the two cannot drift.
MIN_DISCONNECT_GRACE = RECONNECT_DELAY + 0.15
# ops that change the persistent tree and must be replicated/quorum-gated
_MUTATING = frozenset({"create", "set", "delete", "multi"})


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _unb64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


class _Conn:
    def __init__(self, server: "CoordServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session: model.Session | None = None
        self.alive = True
        self.is_follower = False
        self.follower_id: int | None = None
        self.ack_waiters: dict[int, asyncio.Future] = {}

    def push(self, msg: dict) -> None:
        if not self.alive:
            return
        try:
            buffered = self.writer.transport.get_write_buffer_size()
        except (AttributeError, RuntimeError):
            buffered = 0
        if buffered > self.server.max_buffered:
            # slow/stalled subscriber: watch pushes would otherwise
            # buffer unboundedly inside coordd.  Sever it, as ZooKeeper
            # does with slow clients; its session lives on until the
            # timeout, so a healthy client reconnects.
            self.sever()
            return
        try:
            self.writer.write((json.dumps(msg) + "\n").encode())
        except (ConnectionError, RuntimeError):
            self.alive = False

    def sever(self) -> None:
        """Kill the connection immediately (session untouched)."""
        self.alive = False
        try:
            self.writer.transport.abort()
        except (AttributeError, RuntimeError):
            self.writer.close()

    def watch_sink(self, kind: str):
        def sink(event):
            self.push({"watch": {"kind": kind, "type": event.type.value,
                                 "path": event.path}})
        sink.__owner__ = self
        return sink


class CoordServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 tick: float = 0.25, data_dir: str | None = None,
                 ensemble: list[tuple[str, int]] | None = None,
                 ensemble_id: int = 0, promote_grace: float = 2.0,
                 metrics_port: int | None = None):
        """*data_dir*: when set, the persistent tree is snapshotted there
        and reloaded on start (ZooKeeper-parity durability).  Ephemeral
        nodes do not survive a restart — their sessions are gone, and
        clients observe expiry and re-register.

        *ensemble*: full member address list (including this server);
        *ensemble_id* is this server's index into it.  See the module
        docstring for the replication/leadership protocol."""
        self.host = host
        self.port = port
        self.tick = tick
        self.max_buffered = MAX_BUFFERED
        self.data_dir = data_dir
        self.ensemble = ensemble
        self.my_id = ensemble_id
        self.promote_grace = promote_grace
        self.role = "follower" if ensemble else "leader"
        self.leader_addr: tuple[str, int] | None = None
        self._seq = 0
        self._follower_conns: set[_Conn] = set()
        self._reap_tasks: set[asyncio.Task] = set()
        self._follow_task: asyncio.Task | None = None
        self._probe_task: asyncio.Task | None = None
        self._stopping = False
        self.tree = self._load_tree()
        self._server: asyncio.AbstractServer | None = None
        self._expiry_task: asyncio.Task | None = None
        self._save_task: asyncio.Task | None = None
        self._dirty = False
        self._conns: set[_Conn] = set()
        # session id -> live conn (one at a time)
        self._session_conns: dict[str, _Conn] = {}
        self.metrics_port = metrics_port
        self._metrics_runner = None
        self._mutations = 0
        self._wire_tree(self.tree)

    def _wire_tree(self, tree: model.ZNodeTree) -> None:
        """One on_mutate hook per tree: count mutations (for /metrics)
        and schedule persistence when a data dir is configured."""
        def on_mutate():
            self._mutations += 1
            if self.data_dir:
                self._mark_dirty()
        tree.on_mutate = on_mutate

    # ---- persistence ----

    def _snapshot_path(self):
        from pathlib import Path
        return Path(self.data_dir) / "coordd-tree.json"

    def _load_tree(self) -> model.ZNodeTree:
        if not self.data_dir:
            return model.ZNodeTree()
        from pathlib import Path
        Path(self.data_dir).mkdir(parents=True, exist_ok=True)
        path = self._snapshot_path()
        if not path.exists():
            return model.ZNodeTree()
        try:
            snap = json.loads(path.read_text())
            tree = model.ZNodeTree.from_snapshot(snap)
            self._seq = int(snap.get("seq", 0))
            log.info("loaded coordination tree from %s (seq %d)",
                     path, self._seq)
            return tree
        except (ValueError, OSError) as e:
            log.error("cannot load tree snapshot %s: %s; starting empty",
                      path, e)
            return model.ZNodeTree()

    def _mark_dirty(self) -> None:
        self._dirty = True
        if self._save_task is None or self._save_task.done():
            try:
                self._save_task = asyncio.ensure_future(
                    self._save_soon())
            except RuntimeError:
                self._save_now()   # no loop (tests): save synchronously

    async def _save_soon(self) -> None:
        # debounce bursts; one snapshot per 50ms of mutations
        await asyncio.sleep(0.05)
        self._save_now()

    def _save_now(self) -> None:
        if not self.data_dir or not self._dirty:
            return
        self._dirty = False
        path = self._snapshot_path()
        tmp = path.with_name(path.name + ".tmp")
        try:
            snap = self.tree.to_snapshot()
            snap["seq"] = self._seq
            tmp.write_text(json.dumps(snap))
            tmp.replace(path)
        except OSError as e:
            log.error("cannot persist tree snapshot: %s", e)
            self._dirty = True

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_LINE)
        self.port = self._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.ensure_future(self._expiry_loop())
        if self.ensemble:
            self._follow_task = asyncio.ensure_future(self._follow_loop())
        if self.metrics_port is not None:
            await self._start_metrics()
        log.info("coordd listening on %s:%d%s%s", self.host, self.port,
                 " (persistent: %s)" % self.data_dir
                 if self.data_dir else "",
                 " (ensemble id %d of %d)" % (self.my_id, len(self.ensemble))
                 if self.ensemble else "")

    async def stop(self) -> None:
        self._stopping = True
        if self._metrics_runner is not None:
            await self._metrics_runner.cleanup()
            self._metrics_runner = None
        for t in (self._follow_task, self._probe_task):
            if t:
                t.cancel()
        for t in list(self._reap_tasks):
            t.cancel()
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._save_task and not self._save_task.done():
            self._save_task.cancel()
        self._save_now()   # final flush
        # close live connections BEFORE wait_closed(): since 3.12 it waits
        # for every connection handler to finish
        for conn in list(self._conns):
            conn.sever()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # ---- metrics (beyond-parity observability; ZooKeeper exposes the
    # equivalent via four-letter words / its own Prometheus provider) ----

    async def _start_metrics(self) -> None:
        from aiohttp import web

        async def metrics(_req):
            return web.Response(text=self._render_metrics(),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/metrics", metrics)
        self._metrics_runner = web.AppRunner(app)
        await self._metrics_runner.setup()
        site = web.TCPSite(self._metrics_runner, self.host,
                           self.metrics_port)
        await site.start()
        if self.metrics_port == 0:
            self.metrics_port = self._metrics_runner.addresses[0][1]
        log.info("coordd metrics on %s:%d", self.host, self.metrics_port)

    def _render_metrics(self) -> str:
        from manatee_tpu.utils.prom import MetricsBuilder

        b = MetricsBuilder("coordd")
        b.metric("role", "gauge", "this member's current role",
                 [('{role="%s"}' % r, 1 if r == self.role else 0)
                  for r in ("leader", "follower")])
        # a gauge, not a counter: followers jump to the leader's seq on
        # resync and an ex-leader's seq can move backwards when it
        # force-syncs to the incumbent — operators compare seqs ACROSS
        # members, not rates
        b.metric("seq", "gauge",
                 "replication sequence position", self._seq)
        b.metric("mutations_total", "counter",
                 "tree mutations applied by this member",
                 self._mutations)
        b.metric("sessions", "gauge", "live client sessions",
                 sum(1 for s in self.tree.sessions.values()
                     if not s.expired))
        b.metric("connections", "gauge", "open client connections",
                 len(self._conns))
        b.metric("followers_connected", "gauge",
                 "follower members attached (leader only)",
                 len(self._follower_conns))
        if self.ensemble:
            if self.role == "leader":
                # only the leader commits, so only it has a quorum fact;
                # followers omit the series rather than export a
                # permanently-alarming 0
                need = self._quorum_needed()
                have = 1 + len(self._follower_conns)
                b.metric("quorum_ok", "gauge",
                         "1 when this leader can commit mutations",
                         1 if (need is None or have >= need) else 0)
            b.metric("ensemble_size", "gauge",
                     "configured member count", len(self.ensemble))

        def count_nodes(node) -> int:
            return 1 + sum(count_nodes(c) for c in node.children.values())

        b.metric("znodes", "gauge", "nodes in the tree (incl. root)",
                 count_nodes(self.tree._root))
        b.metric("watches", "gauge", "registered one-shot watches",
                 sum(len(v) for v in self.tree._watches.values()))
        return b.render()

    def _expire_due_sessions(self) -> None:
        for sid in self.tree.expired_sessions():
            log.info("session %s expired", sid)
            self.tree.expire_session(sid)
            self.tree.sessions.pop(sid, None)
            conn = self._session_conns.pop(sid, None)
            if conn is not None:
                # hung-but-connected client: sever the socket so it
                # observes expiry instead of lingering half-alive
                conn.sever()

    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick)
            self._expire_due_sessions()

    # ---- per-connection ----

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ConnectionError, ValueError):
                    # ValueError = line over the stream limit
                    break
                if not line:
                    break
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    conn.push({"ok": False, "error": "CoordError",
                               "msg": "bad json"})
                    continue
                await self._dispatch(conn, req)
                try:
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    break
        finally:
            conn.alive = False
            self._conns.discard(conn)
            self._follower_conns.discard(conn)
            for fut in conn.ack_waiters.values():
                if not fut.done():
                    fut.cancel()
            # the session survives the connection; watches don't
            self.tree.remove_watches_for(
                lambda w: getattr(w, "__owner__", None) is conn)
            if conn.session and not conn.session.expired \
                    and self._session_conns.get(conn.session.id) is conn:
                # only if the session wasn't already resumed elsewhere
                del self._session_conns[conn.session.id]
                conn.session.connected = False
                conn.session.last_seen = time.monotonic()
                conn.session.disconnected_at = conn.session.last_seen
                if conn.session.disconnect_grace is not None:
                    # precise fast-path expiry: don't leave the grace
                    # quantized by the periodic tick (a failover waits
                    # on this deadline)
                    asyncio.get_running_loop().call_later(
                        conn.session.disconnect_grace + 0.005,
                        self._expire_due_sessions)
            writer.close()

    async def _dispatch(self, conn: _Conn, req: dict) -> None:
        xid = req.get("xid")
        op = req.get("op")
        try:
            if op == "sync_ack":
                # follower ack of a replicated snapshot: resolve the
                # waiter, no reply (acks must not generate traffic)
                fut = conn.ack_waiters.pop(int(req.get("seq", -1)), None)
                if fut and not fut.done():
                    fut.set_result(True)
                return
            if op == "hello":
                result = self._op_hello(conn, req)
            elif op == "sync_status":
                result = self._op_sync_status()
            elif op == "sync_hello":
                result = self._op_sync_hello(conn, req)
            elif conn.session is None or conn.session.expired:
                raise CoordError("no session (hello first)")
            else:
                self.tree.touch_session(conn.session.id)
                mutating = op in _MUTATING
                mode = None
                if mutating:
                    self._check_quorum()
                    # classify BEFORE applying: an ephemeral delete
                    # target is gone afterwards
                    mode = self._replication_mode(op, req)
                result = self._op(conn, op, req)
                if mutating and mode is not None:
                    self._seq += 1
                    if mode == "op":
                        acks = await self._replicate_op(req, result)
                    else:
                        acks = await self._replicate_snapshot()
                    self._check_commit_quorum(acks)
            conn.push({"xid": xid, "ok": True, "result": result})
        except NotLeaderError as e:
            reply = {"xid": xid, "ok": False, "error": "NotLeaderError",
                     "msg": str(e)}
            if self.leader_addr is not None:
                reply["leader"] = "%s:%d" % self.leader_addr
            conn.push(reply)
        except CoordError as e:
            conn.push({"xid": xid, "ok": False,
                       "error": _ERR_NAMES.get(type(e), "CoordError"),
                       "msg": str(e)})
        except Exception as e:
            # malformed-but-valid-JSON requests must get an error reply,
            # not kill the connection
            log.warning("bad request %r: %s", op, e)
            conn.push({"xid": xid, "ok": False, "error": "CoordError",
                       "msg": "bad request: %s" % e})

    def _op_hello(self, conn: _Conn, req: dict):
        if self.ensemble and self.role != "leader":
            raise NotLeaderError("member %d is not the leader" % self.my_id)
        sid = req.get("session_id")
        if sid:
            sess = self.tree.sessions.get(sid)
            if not sess or sess.expired:
                raise CoordError("session expired: %s" % sid)
            old = self._session_conns.get(sid)
            if old and old is not conn:
                old.sever()
        else:
            # Floor: a timeout at or below the ping interval would
            # perpetually expire healthy sessions now that connected
            # sessions are subject to heartbeat expiry (ZK likewise
            # clamps to a server-side minimum of 2 ticks).
            timeout = max(float(req.get("session_timeout", 60.0)),
                          4 * self.tick)
            grace = req.get("disconnect_grace")
            if grace is not None:
                # must outlive the expiry tick and the client's
                # reconnect delay, or a transient drop could never be
                # resumed before the fast path expires it
                grace = max(float(grace), 2 * self.tick,
                            MIN_DISCONNECT_GRACE)
            sess = self.tree.create_session(timeout,
                                            disconnect_grace=grace)
        sess.connected = True
        sess.last_seen = time.monotonic()
        sess.disconnected_at = None
        conn.session = sess
        self._session_conns[sess.id] = conn
        # report the EFFECTIVE (possibly floored) values so the client
        # can reason from what the server will actually enforce
        return {"session_id": sess.id, "session_timeout": sess.timeout,
                "disconnect_grace": sess.disconnect_grace}

    def _op(self, conn: _Conn, op: str, req: dict):
        tree = self.tree
        path = req.get("path", "")
        if op == "ping":
            return "pong"
        if op == "goodbye":
            # explicit session end: ephemerals vanish NOW, like closing a
            # ZooKeeper handle (and like MemoryCoord.close()).  Without
            # this a cleanly-shut-down peer lingers in the election until
            # its session times out.
            sid = conn.session.id
            tree.expire_session(sid)
            tree.sessions.pop(sid, None)
            self._session_conns.pop(sid, None)
            return "bye"
        if op == "create":
            return tree.create(
                path, _unb64(req.get("data")),
                ephemeral_owner=(conn.session.id if req.get("ephemeral")
                                 else None),
                sequential=bool(req.get("sequential")))
        if op == "get":
            data, version = tree.get(path)
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            return {"data": _b64(data), "version": version,
                    "ctime": stat.ctime if stat else 0.0}
        if op == "set":
            return tree.set(path, _unb64(req.get("data")),
                            int(req.get("version", -1)))
        if op == "delete":
            tree.delete(path, int(req.get("version", -1)))
            return None
        if op == "exists":
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            if stat is None:
                return None
            return {"version": stat.version,
                    "ephemeral_owner": stat.ephemeral_owner,
                    "num_children": stat.num_children,
                    "ctime": stat.ctime}
        if op == "children":
            names = tree.get_children(path)
            if req.get("watch"):
                tree.add_watch(model.CHILDREN, path,
                               conn.watch_sink(model.CHILDREN))
            return names
        if op == "multi":
            ops = []
            for o in req.get("ops", []):
                ops.append(Op(
                    kind=o["kind"], path=o["path"],
                    data=_unb64(o.get("data")),
                    version=int(o.get("version", -1)),
                    ephemeral=bool(o.get("ephemeral")),
                    sequential=bool(o.get("sequential"))))
            return tree.multi(ops, session_id=conn.session.id)
        raise CoordError("unknown op: %r" % op)

    # ---- ensemble: leader side ----

    def _op_sync_status(self) -> dict:
        return {"role": self.role, "seq": self._seq, "id": self.my_id,
                "leader": ("%s:%d" % self.leader_addr
                           if self.leader_addr else None)}

    def _op_sync_hello(self, conn: _Conn, req: dict) -> dict:
        if self.role != "leader":
            raise NotLeaderError("member %d is not the leader" % self.my_id)
        fid = req.get("id")
        # dedupe by member id: a resyncing follower's stale half-dead
        # connection must not keep counting toward quorum
        for old in list(self._follower_conns):
            if old.follower_id == fid and old is not conn:
                self._follower_conns.discard(old)
                old.sever()
        conn.is_follower = True
        conn.follower_id = fid
        self._follower_conns.add(conn)
        log.info("follower %s joined (seq %d)", fid, self._seq)
        snap = self.tree.to_snapshot()
        return {"seq": self._seq, "snapshot": snap}

    def _quorum_needed(self) -> int | None:
        """Members (incl. self) that must hold a write, or None when no
        quorum applies (standalone, or a 2-member ensemble — which has
        no safe quorum smaller than itself; there we prioritize
        availability and document the tradeoff)."""
        if not self.ensemble or len(self.ensemble) < 3:
            return None
        return len(self.ensemble) // 2 + 1

    def _check_quorum(self) -> None:
        """Cheap pre-check: refuse mutations outright when not even a
        majority of followers is connected."""
        need = self._quorum_needed()
        if need is not None and 1 + len(self._follower_conns) < need:
            raise CoordError(
                "no quorum: %d of %d ensemble members connected"
                % (1 + len(self._follower_conns), len(self.ensemble)))

    def _check_commit_quorum(self, acks: int) -> None:
        """Post-replication check: an acked client write must exist on a
        majority, or a partitioned minority leader could acknowledge
        writes the eventual winner never saw.  The op is already applied
        locally; refusing here makes the failure AMBIGUOUS to the client
        (as in ZooKeeper connection loss) rather than silently lossy."""
        need = self._quorum_needed()
        if need is not None and 1 + acks < need:
            raise CoordError(
                "no quorum: write replicated to %d of %d members "
                "(uncommitted; retry may see it applied)"
                % (1 + acks, len(self.ensemble)))

    def _replication_mode(self, op: str, req: dict) -> str | None:
        """How a mutation reaches followers: 'op' (ship the op itself),
        'snapshot' (rare fallback), or None (no persistent effect —
        ephemerals live only on the leader, so there is nothing to
        ship; election joins/leaves stay O(0) for the ensemble).

        Unshipped ephemeral-sequential creates mean the counter of a
        parent like election/ runs ahead on the leader; that is safe:
        the counter only names EPHEMERAL children, which die with their
        sessions at failover, so a promoted follower's lower counter
        cannot collide with anything still alive."""
        if op == "create":
            return None if req.get("ephemeral") else "op"
        if op in ("set", "delete"):
            stat = self.tree.exists(req.get("path", ""))
            if stat is not None and stat.ephemeral_owner is not None:
                return None
            return "op"
        if op == "multi":
            # our transactions (putClusterState) are persistent-only; a
            # transaction that CREATES an ephemeral, or sets/deletes an
            # existing one, has effects followers must not (create) or
            # cannot (set/delete a node they do not hold) apply — fall
            # back to the full snapshot, which carries exactly the
            # persistent outcome
            for o in req.get("ops", []):
                if o.get("ephemeral"):
                    return "snapshot"
                if o.get("kind") in ("set", "delete"):
                    stat = self.tree.exists(o.get("path", ""))
                    if stat is not None and \
                            stat.ephemeral_owner is not None:
                        return "snapshot"
            return "op"
        return "op"

    async def _replicate_op(self, req: dict, result) -> int:
        """Ship one persistent mutation as the op itself — O(op), not
        O(tree).  *result* rides along so followers can verify their
        apply produced the same outcome (sequential names, versions)."""
        wire = {k: req[k] for k in ("op", "path", "data", "version",
                                    "sequential", "ops") if k in req}
        return await self._ship(
            {"sync_op": {"seq": self._seq, "req": wire, "expect": result}})

    async def _replicate_snapshot(self) -> int:
        """Ship the full persistent tree (follower attach + the rare
        mixed-transaction fallback)."""
        return await self._ship(
            {"sync": {"seq": self._seq,
                      "snapshot": self.tree.to_snapshot()}})

    async def _ship(self, msg: dict) -> int:
        """Push *msg* (carrying the current seq) to every follower and
        collect acks.  Returns as soon as enough followers for a commit
        quorum have acked — a hung follower must not add its full fault
        budget to every client write (a SIGSTOPped member once cost
        every putClusterState, takeovers included, up to 1s here).
        Laggards keep the rest of the fault budget in the background and
        are severed if still silent (they resync with a fresh
        sync_hello).  Returns the number of followers acked so far."""
        if not self._follower_conns:
            return 0
        seq = self._seq
        loop = asyncio.get_running_loop()
        waiters: list[tuple[_Conn, asyncio.Future]] = []
        for f in list(self._follower_conns):
            fut = loop.create_future()
            f.ack_waiters[seq] = fut
            f.push(msg)
            waiters.append((f, fut))
        need = self._quorum_needed()
        # followers needed beyond ourselves; no-quorum ensembles (2
        # members) keep wait-for-all semantics — there is no safe
        # subset to commit on
        need_f = len(waiters) if need is None else min(need - 1,
                                                       len(waiters))
        # the fault budget scales with tick (the reference's analogue is
        # ZooKeeper's tick-derived timeouts), floored so a slow-but-live
        # follower on a loaded host is not severed spuriously
        deadline = loop.time() + max(4 * self.tick, 1.0)
        pending = {fut for _f, fut in waiters}
        acks = 0
        while pending:
            done, pending = await asyncio.wait(
                pending, timeout=max(0.0, deadline - loop.time()),
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                break                      # deadline hit
            acks += sum(1 for d in done if not d.cancelled())
            if acks >= need_f:
                break
        laggards = [(f, fut) for f, fut in waiters if not fut.done()]
        if laggards:
            # strong refs: the loop holds tasks weakly and a GC'd
            # reaper would leave hung followers connected forever
            t = asyncio.ensure_future(
                self._reap_laggards(seq, laggards, deadline))
            self._reap_tasks.add(t)
            t.add_done_callback(self._reap_tasks.discard)
        return acks

    async def _reap_laggards(self, seq: int,
                             waiters: list, deadline: float) -> None:
        """Give not-yet-acked followers the remainder of the fault
        budget off the write path, then sever the still-silent ones."""
        loop = asyncio.get_running_loop()
        remaining = deadline - loop.time()
        if remaining > 0:
            await asyncio.wait([fut for _f, fut in waiters],
                               timeout=remaining)
        for f, fut in waiters:
            if not fut.done():
                f.ack_waiters.pop(seq, None)
                log.warning("follower not acking seq %d; severing", seq)
                self._follower_conns.discard(f)
                f.sever()

    async def _leader_probe_loop(self) -> None:
        """Leader heartbeat to followers + dual-leader resolution after a
        partition heal: the leader with (higher seq, then lower id) wins;
        the other steps down."""
        interval = max(self.tick * 2, 0.5)
        while not self._stopping and self.role == "leader":
            await asyncio.sleep(interval)
            for f in list(self._follower_conns):
                f.push({"sync_ping": {"seq": self._seq}})
            for idx, addr in enumerate(self.ensemble):
                if idx == self.my_id:
                    continue
                st = await self._probe(addr)
                if st and st.get("role") == "leader":
                    if (st.get("seq", 0) > self._seq
                            or (st.get("seq", 0) == self._seq
                                and idx < self.my_id)):
                        self._step_down("dual leader: member %d seq %s wins"
                                        % (idx, st.get("seq")))
                        break

    def _become_leader(self) -> None:
        log.warning("promoting to ensemble leader (id %d, seq %d)",
                    self.my_id, self._seq)
        self.role = "leader"
        self.leader_addr = self.ensemble[self.my_id]
        if self._probe_task is None or self._probe_task.done():
            self._probe_task = asyncio.ensure_future(
                self._leader_probe_loop())

    def _step_down(self, why: str) -> None:
        log.warning("stepping down from leader: %s", why)
        self.role = "follower"
        self.leader_addr = None
        # sessions (and their ephemerals) die with leadership: clients
        # observe expiry and re-register on the winning leader
        for sid in list(self.tree.sessions):
            self.tree.expire_session(sid)
        self.tree.sessions.clear()
        self._session_conns.clear()
        self._follower_conns.clear()
        for conn in list(self._conns):
            conn.sever()
        if self._follow_task is None or self._follow_task.done():
            self._follow_task = asyncio.ensure_future(self._follow_loop())

    # ---- ensemble: follower side ----

    async def _probe(self, addr: tuple[str, int]) -> dict | None:
        """One-shot sync_status request to another member; None if it
        does not answer promptly."""
        from manatee_tpu.coord.client import sync_status
        return await sync_status(addr[0], addr[1], 0.5)

    async def _follow_loop(self) -> None:
        """Find and follow the leader; promote when, for promote_grace,
        a QUORUM of members is reachable and none of them outranks us.
        Rank is (seq, then lowest id): a member with a newer persisted
        tree must win or its committed writes would be rolled back;
        among equals the lowest id wins.  A reachable outranking
        non-leader resets the clock — it is deciding too and will
        promote.

        The quorum-contact requirement is what makes election safe
        against the double fault ZooKeeper also excludes: a
        majority-acked write lives on ≥ quorum members, any two quorums
        intersect, so a candidate that contacted a quorum and outranks
        all of it cannot be missing an acked write — a laggard that can
        only see a minority never self-promotes, no matter how long the
        up-to-date members stay unreachable."""
        interval = max(self.tick, 0.2)
        need = self._quorum_needed()
        unranked_since: float | None = None
        while not self._stopping and self.role != "leader":
            leader: tuple[str, int] | None = None
            outranked = False
            reachable = 1                     # self
            for idx, addr in enumerate(self.ensemble):
                if idx == self.my_id:
                    continue
                st = await self._probe(addr)
                if st is None:
                    continue
                reachable += 1
                if st.get("role") == "leader":
                    leader = addr
                    break
                peer_seq = int(st.get("seq", 0))
                if peer_seq > self._seq or \
                        (peer_seq == self._seq and idx < self.my_id):
                    outranked = True
            if leader is not None:
                unranked_since = None
                try:
                    await self._follow(leader)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.info("follow of %s:%d ended: %s",
                             leader[0], leader[1], e)
                # fall through to the sleep: a fast-failing follow must
                # not busy-loop full-snapshot resyncs against the leader
            elif outranked or (need is not None and reachable < need):
                unranked_since = None
            else:
                now = time.monotonic()
                if unranked_since is None:
                    unranked_since = now
                elif now - unranked_since >= self.promote_grace:
                    self._become_leader()
                    return
            await asyncio.sleep(interval)

    async def _follow(self, addr: tuple[str, int]) -> None:
        """Stream snapshots from the leader until the connection dies or
        we are no longer a follower."""
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(addr[0], addr[1], limit=MAX_LINE), 1.0)
        try:
            writer.write((json.dumps(
                {"op": "sync_hello", "xid": 0,
                 "id": self.my_id, "seq": self._seq}) + "\n").encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), 2.0)
            msg = json.loads(line)
            if not msg.get("ok"):
                raise CoordError("sync_hello refused: %s" % msg.get("msg"))
            res = msg["result"]
            # the full resync is authoritative: adopt the leader's tree
            # even if our (possibly debounce-lost or divergent) seq is
            # higher, or we would livelock re-resyncing forever
            self._apply_sync(int(res["seq"]), res["snapshot"], force=True)
            self.leader_addr = addr
            log.info("following leader %s:%d (seq %d)",
                     addr[0], addr[1], self._seq)
            # leader pings every probe interval; silence means it is
            # gone (or wedged) and we must re-elect
            idle = max(2.0, 6 * self.tick)
            while not self._stopping and self.role == "follower":
                line = await asyncio.wait_for(reader.readline(), idle)
                if not line:
                    break
                msg = json.loads(line)
                if "sync" in msg:
                    s = msg["sync"]
                    self._apply_sync(int(s["seq"]), s["snapshot"])
                    writer.write((json.dumps(
                        {"op": "sync_ack", "seq": s["seq"]}) + "\n").encode())
                    await writer.drain()
                elif "sync_op" in msg:
                    s = msg["sync_op"]
                    seq = int(s["seq"])
                    if seq != self._seq + 1:
                        break   # gap: resync with a fresh sync_hello
                    try:
                        got = self._apply_op(s.get("req") or {})
                    except CoordError as e:
                        log.warning("replicated op failed (diverged?): "
                                    "%s; resyncing", e)
                        break
                    if s.get("expect", got) != got:
                        log.warning("replicated op result %r != leader's "
                                    "%r; resyncing", got, s.get("expect"))
                        break
                    self._seq = seq
                    writer.write((json.dumps(
                        {"op": "sync_ack", "seq": seq}) + "\n").encode())
                    await writer.drain()
                elif "sync_ping" in msg:
                    if int(msg["sync_ping"].get("seq", -1)) != self._seq:
                        break   # drifted; resync with a fresh sync_hello
        finally:
            self.leader_addr = None
            try:
                writer.close()
            except RuntimeError:
                pass

    def _apply_op(self, r: dict):
        """Apply one leader-replicated persistent mutation to the local
        tree.  Followers hold only the persistent tree: no sessions, no
        ephemerals, no client watches.  Version checks run against OUR
        tree — a BadVersionError here means we diverged from the leader
        and the caller falls back to a full resync."""
        op = r.get("op")
        if op == "create":
            return self.tree.create(r["path"], _unb64(r.get("data")),
                                    sequential=bool(r.get("sequential")))
        if op == "set":
            return self.tree.set(r["path"], _unb64(r.get("data")),
                                 int(r.get("version", -1)))
        if op == "delete":
            self.tree.delete(r["path"], int(r.get("version", -1)))
            return None
        if op == "multi":
            ops = [Op(kind=o["kind"], path=o["path"],
                      data=_unb64(o.get("data")),
                      version=int(o.get("version", -1)),
                      ephemeral=False,
                      sequential=bool(o.get("sequential")))
                   for o in r.get("ops", [])]
            return self.tree.multi(ops, session_id=None)
        raise CoordError("unknown replicated op: %r" % op)

    def _apply_sync(self, seq: int, snap: dict, *,
                    force: bool = False) -> None:
        if seq < self._seq and not force:
            return
        tree = model.ZNodeTree.from_snapshot(snap)
        self.tree = tree
        self._seq = seq
        self._wire_tree(tree)
        if self.data_dir:
            self._mark_dirty()


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="manatee coordination daemon")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2281)
    p.add_argument("--data-dir", default=None,
                   help="persist the tree here (survives restarts)")
    p.add_argument("--tick", type=float, default=0.25,
                   help="session-expiry scan interval (seconds)")
    p.add_argument("--ensemble", default=None,
                   help="full member list 'h1:p1,h2:p2,...' incl. this "
                        "server (replicated mode)")
    p.add_argument("--ensemble-id", type=int, default=0,
                   help="this server's index into --ensemble")
    p.add_argument("--promote-grace", type=float, default=2.0,
                   help="seconds of lower-member unreachability before a "
                        "follower promotes itself")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus /metrics on this port "
                        "(default: disabled)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    setup_logging("manatee-coordd", args.verbose)

    ensemble = None
    if args.ensemble:
        from manatee_tpu.coord.client import parse_connstr
        ensemble = parse_connstr(args.ensemble)

    async def run():
        server = CoordServer(args.host, args.port, tick=args.tick,
                             data_dir=args.data_dir,
                             ensemble=ensemble,
                             ensemble_id=args.ensemble_id,
                             promote_grace=args.promote_grace,
                             metrics_port=args.metrics_port)
        await server.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
