"""coordd — the coordination service daemon.

Plays the role ZooKeeper plays for the reference: znode tree with
versioned CAS writes, ephemeral-sequential nodes, one-shot watches,
transactions, and session-timeout liveness (a SIGKILLed peer's ephemeral
nodes vanish only after its session times out, which is exactly the
failure-detection path of SURVEY.md §5.3).

Wire protocol: newline-delimited JSON over TCP.

  client -> server   {"xid": 1, "op": "create", "path": "/a", "data": "<b64>",
                      "ephemeral": true, "sequential": true}
  server -> client   {"xid": 1, "ok": true, "result": "/a0000000001"}
                     {"xid": 1, "ok": false, "error": "NoNodeError", "msg": "..."}
  watch push         {"watch": {"kind": "data", "type": "deleted", "path": "/a"}}

Sessions: ``hello`` creates (or resumes) a session; a dropped TCP
connection leaves the session alive until ``session_timeout`` elapses.
In production this daemon would run as an ensemble; for the single-host
deployments this rebuild targets it runs as one process (the reference
likewise tolerates a single-node ZK in dev, docs/working-on-manatee.md).
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import logging
import signal
import time

from manatee_tpu.coord import model
from manatee_tpu.coord.api import (
    BadVersionError,
    CoordError,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    Op,
)
from manatee_tpu.utils.logutil import setup_logging

log = logging.getLogger("manatee.coordd")

_ERR_NAMES = {
    NoNodeError: "NoNodeError",
    NodeExistsError: "NodeExistsError",
    BadVersionError: "BadVersionError",
    NotEmptyError: "NotEmptyError",
}

MAX_LINE = 8 * 1024 * 1024
# per-connection outbound buffer cap; beyond this the subscriber is
# considered stalled and its connection is aborted (ADVICE r1)
MAX_BUFFERED = 16 * 1024 * 1024


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _unb64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


class _Conn:
    def __init__(self, server: "CoordServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session: model.Session | None = None
        self.alive = True

    def push(self, msg: dict) -> None:
        if not self.alive:
            return
        try:
            buffered = self.writer.transport.get_write_buffer_size()
        except (AttributeError, RuntimeError):
            buffered = 0
        if buffered > self.server.max_buffered:
            # slow/stalled subscriber: watch pushes would otherwise
            # buffer unboundedly inside coordd.  Sever it, as ZooKeeper
            # does with slow clients; its session lives on until the
            # timeout, so a healthy client reconnects.
            self.sever()
            return
        try:
            self.writer.write((json.dumps(msg) + "\n").encode())
        except (ConnectionError, RuntimeError):
            self.alive = False

    def sever(self) -> None:
        """Kill the connection immediately (session untouched)."""
        self.alive = False
        try:
            self.writer.transport.abort()
        except (AttributeError, RuntimeError):
            self.writer.close()

    def watch_sink(self, kind: str):
        def sink(event):
            self.push({"watch": {"kind": kind, "type": event.type.value,
                                 "path": event.path}})
        sink.__owner__ = self
        return sink


class CoordServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 tick: float = 0.25, data_dir: str | None = None):
        """*data_dir*: when set, the persistent tree is snapshotted there
        and reloaded on start (ZooKeeper-parity durability).  Ephemeral
        nodes do not survive a restart — their sessions are gone, and
        clients observe expiry and re-register."""
        self.host = host
        self.port = port
        self.tick = tick
        self.max_buffered = MAX_BUFFERED
        self.data_dir = data_dir
        self.tree = self._load_tree()
        self._server: asyncio.AbstractServer | None = None
        self._expiry_task: asyncio.Task | None = None
        self._save_task: asyncio.Task | None = None
        self._dirty = False
        self._conns: set[_Conn] = set()
        # session id -> live conn (one at a time)
        self._session_conns: dict[str, _Conn] = {}
        if self.data_dir:
            self.tree.on_mutate = self._mark_dirty

    # ---- persistence ----

    def _snapshot_path(self):
        from pathlib import Path
        return Path(self.data_dir) / "coordd-tree.json"

    def _load_tree(self) -> model.ZNodeTree:
        if not self.data_dir:
            return model.ZNodeTree()
        from pathlib import Path
        Path(self.data_dir).mkdir(parents=True, exist_ok=True)
        path = self._snapshot_path()
        if not path.exists():
            return model.ZNodeTree()
        try:
            snap = json.loads(path.read_text())
            tree = model.ZNodeTree.from_snapshot(snap)
            log.info("loaded coordination tree from %s", path)
            return tree
        except (ValueError, OSError) as e:
            log.error("cannot load tree snapshot %s: %s; starting empty",
                      path, e)
            return model.ZNodeTree()

    def _mark_dirty(self) -> None:
        self._dirty = True
        if self._save_task is None or self._save_task.done():
            try:
                self._save_task = asyncio.ensure_future(
                    self._save_soon())
            except RuntimeError:
                self._save_now()   # no loop (tests): save synchronously

    async def _save_soon(self) -> None:
        # debounce bursts; one snapshot per 50ms of mutations
        await asyncio.sleep(0.05)
        self._save_now()

    def _save_now(self) -> None:
        if not self.data_dir or not self._dirty:
            return
        self._dirty = False
        path = self._snapshot_path()
        tmp = path.with_name(path.name + ".tmp")
        try:
            tmp.write_text(json.dumps(self.tree.to_snapshot()))
            tmp.replace(path)
        except OSError as e:
            log.error("cannot persist tree snapshot: %s", e)
            self._dirty = True

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_LINE)
        self.port = self._server.sockets[0].getsockname()[1]
        self._expiry_task = asyncio.ensure_future(self._expiry_loop())
        log.info("coordd listening on %s:%d%s", self.host, self.port,
                 " (persistent: %s)" % self.data_dir
                 if self.data_dir else "")

    async def stop(self) -> None:
        if self._expiry_task:
            self._expiry_task.cancel()
        if self._save_task and not self._save_task.done():
            self._save_task.cancel()
        self._save_now()   # final flush
        # close live connections BEFORE wait_closed(): since 3.12 it waits
        # for every connection handler to finish
        for conn in list(self._conns):
            conn.sever()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick)
            for sid in self.tree.expired_sessions():
                log.info("session %s expired", sid)
                self.tree.expire_session(sid)
                self.tree.sessions.pop(sid, None)
                conn = self._session_conns.pop(sid, None)
                if conn is not None:
                    # hung-but-connected client: sever the socket so it
                    # observes expiry instead of lingering half-alive
                    conn.sever()

    # ---- per-connection ----

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ConnectionError, ValueError):
                    # ValueError = line over the stream limit
                    break
                if not line:
                    break
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    conn.push({"ok": False, "error": "CoordError",
                               "msg": "bad json"})
                    continue
                await self._dispatch(conn, req)
                try:
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    break
        finally:
            conn.alive = False
            self._conns.discard(conn)
            # the session survives the connection; watches don't
            self.tree.remove_watches_for(
                lambda w: getattr(w, "__owner__", None) is conn)
            if conn.session and not conn.session.expired \
                    and self._session_conns.get(conn.session.id) is conn:
                # only if the session wasn't already resumed elsewhere
                del self._session_conns[conn.session.id]
                conn.session.connected = False
                conn.session.last_seen = time.monotonic()
            writer.close()

    async def _dispatch(self, conn: _Conn, req: dict) -> None:
        xid = req.get("xid")
        op = req.get("op")
        try:
            if op == "hello":
                result = self._op_hello(conn, req)
            elif conn.session is None or conn.session.expired:
                raise CoordError("no session (hello first)")
            else:
                self.tree.touch_session(conn.session.id)
                result = self._op(conn, op, req)
            conn.push({"xid": xid, "ok": True, "result": result})
        except CoordError as e:
            conn.push({"xid": xid, "ok": False,
                       "error": _ERR_NAMES.get(type(e), "CoordError"),
                       "msg": str(e)})
        except Exception as e:
            # malformed-but-valid-JSON requests must get an error reply,
            # not kill the connection
            log.warning("bad request %r: %s", op, e)
            conn.push({"xid": xid, "ok": False, "error": "CoordError",
                       "msg": "bad request: %s" % e})

    def _op_hello(self, conn: _Conn, req: dict):
        sid = req.get("session_id")
        if sid:
            sess = self.tree.sessions.get(sid)
            if not sess or sess.expired:
                raise CoordError("session expired: %s" % sid)
            old = self._session_conns.get(sid)
            if old and old is not conn:
                old.sever()
        else:
            # Floor: a timeout at or below the ping interval would
            # perpetually expire healthy sessions now that connected
            # sessions are subject to heartbeat expiry (ZK likewise
            # clamps to a server-side minimum of 2 ticks).
            timeout = max(float(req.get("session_timeout", 60.0)),
                          4 * self.tick)
            sess = self.tree.create_session(timeout)
        sess.connected = True
        sess.last_seen = time.monotonic()
        conn.session = sess
        self._session_conns[sess.id] = conn
        return {"session_id": sess.id, "session_timeout": sess.timeout}

    def _op(self, conn: _Conn, op: str, req: dict):
        tree = self.tree
        path = req.get("path", "")
        if op == "ping":
            return "pong"
        if op == "create":
            return tree.create(
                path, _unb64(req.get("data")),
                ephemeral_owner=(conn.session.id if req.get("ephemeral")
                                 else None),
                sequential=bool(req.get("sequential")))
        if op == "get":
            data, version = tree.get(path)
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            return {"data": _b64(data), "version": version,
                    "ctime": stat.ctime if stat else 0.0}
        if op == "set":
            return tree.set(path, _unb64(req.get("data")),
                            int(req.get("version", -1)))
        if op == "delete":
            tree.delete(path, int(req.get("version", -1)))
            return None
        if op == "exists":
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            if stat is None:
                return None
            return {"version": stat.version,
                    "ephemeral_owner": stat.ephemeral_owner,
                    "num_children": stat.num_children,
                    "ctime": stat.ctime}
        if op == "children":
            names = tree.get_children(path)
            if req.get("watch"):
                tree.add_watch(model.CHILDREN, path,
                               conn.watch_sink(model.CHILDREN))
            return names
        if op == "multi":
            ops = []
            for o in req.get("ops", []):
                ops.append(Op(
                    kind=o["kind"], path=o["path"],
                    data=_unb64(o.get("data")),
                    version=int(o.get("version", -1)),
                    ephemeral=bool(o.get("ephemeral")),
                    sequential=bool(o.get("sequential"))))
            return tree.multi(ops, session_id=conn.session.id)
        raise CoordError("unknown op: %r" % op)


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="manatee coordination daemon")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2281)
    p.add_argument("--data-dir", default=None,
                   help="persist the tree here (survives restarts)")
    p.add_argument("--tick", type=float, default=0.25,
                   help="session-expiry scan interval (seconds)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    setup_logging("manatee-coordd", args.verbose)

    async def run():
        server = CoordServer(args.host, args.port, tick=args.tick,
                             data_dir=args.data_dir)
        await server.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
