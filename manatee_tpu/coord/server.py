"""coordd — the coordination service daemon.

Plays the role ZooKeeper plays for the reference: znode tree with
versioned CAS writes, ephemeral-sequential nodes, one-shot watches,
transactions, and session-timeout liveness (a SIGKILLed peer's ephemeral
nodes vanish only after its session times out, which is exactly the
failure-detection path of SURVEY.md §5.3).

Wire protocol: newline-delimited JSON over TCP.

  client -> server   {"xid": 1, "op": "create", "path": "/a", "data": "<b64>",
                      "ephemeral": true, "sequential": true}
  server -> client   {"xid": 1, "ok": true, "result": "/a0000000001"}
                     {"xid": 1, "ok": false, "error": "NoNodeError", "msg": "..."}
  watch push         {"watch": {"kind": "data", "type": "deleted", "path": "/a"}}

Sessions: ``hello`` creates (or resumes) a session; a dropped TCP
connection leaves the session alive until ``session_timeout`` elapses —
unless the client opted into a ``disconnect_grace``, in which case a
*disconnected* session expires after that (shorter) grace.  The grace
is the fast crash-detection path: a SIGKILLed peer's kernel FINs its
socket immediately, so coordd can distinguish "process died" (FIN, then
silence) from "process wedged or partitioned" (no FIN; full heartbeat
timeout applies).  ZooKeeper cannot make this distinction — its clients
talk through a session abstraction that deliberately hides connection
state.  ``goodbye`` ends a session explicitly (ephemeral nodes vanish
at once), matching ZooKeeper handle close.

Ensemble mode (--ensemble/--ensemble-id) replicates coordd the way the
reference assumes a ZooKeeper ensemble (etc/sitter.json zkCfg.connStr):

- exactly one member is *leader* and accepts client sessions; followers
  refuse hello with NotLeaderError + a leader hint, and clients rotate
  (NetCoord multi-address).
- the leader ships the persistent tree (snapshot + monotonic seq) to
  followers on every mutation and awaits their acks; with >=3 members
  mutations additionally require a connected majority (no-quorum
  refusal), so a partitioned minority leader cannot diverge the state.
- leadership: highest (seq, lowest id) among a contacted QUORUM wins —
  a follower promotes itself only after reaching a majority of members
  and outranking all of them for promote_grace, so a laggard cut off
  from the up-to-date members can never roll back a majority-acked
  write (the same two-quorums-intersect argument ZooKeeper elections
  rest on).  A returning member always joins an incumbent leader
  instead of reclaiming (leader stickiness).  Dual leaders after a
  partition heal resolve by (seq, lowest id).
- ephemerals/sessions are deliberately NOT replicated: on failover
  clients observe session loss and re-register — the same contract as
  a coordd restart, and the recovery path ConsensusMgr already owns.

This is op-shipping primary/backup, not ZAB/Raft: it needs the quorum
rule above for safety and trades some availability (a two-member
ensemble cannot survive a partition safely).  A follower attaches with
one full-snapshot resync (sync_hello), then receives each persistent
mutation as the op itself — O(op), independent of tree/history size —
and applies it in sequence, acking the seq.  Any gap, version
conflict, or result mismatch on apply means divergence and triggers a
fresh full resync; ephemeral-only mutations (election joins) touch no
persistent state and are not shipped at all.  The CoordClient
interface stays narrow so a real ZK ensemble could back production via
an adapter.

Durability (--data-dir): ZooKeeper's contract — the one manatee's
deposed/generation records ride on (lib/zookeeperMgr.js:605-630,
docs/xlog-diverge.md) — is that a mutation hits a quorum's fsynced
transaction logs BEFORE it is acknowledged.  Same here: every
persistent mutation is appended to a per-member op log
(coordd-oplog.jsonl) and fsynced before the leader replies to the
client, and before a follower acks the leader's sync_op (so a
majority-acked write is on a majority of DISKS, not a majority of page
caches).  The whole-tree JSON snapshot is demoted to a compaction
artifact: the log rolls over to a fresh numbered segment and a snapshot
covering the old ones is written in a worker thread every
*snapshot_every* logged ops or 64 MB of log (ZooKeeper's
snapCount/log-roll design), then the covered segments are deleted —
per-mutation persistence cost is O(op), independent of tree/history
size, exactly like replication.  Recovery = load snapshot, then replay
segment entries with seq beyond it; a torn final line (crash
mid-append, necessarily unacked) is discarded.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import logging
import os
import signal
import time
from pathlib import Path

from manatee_tpu import faults
from manatee_tpu.coord import model
from manatee_tpu.coord.api import (
    RECONNECT_DELAY,
    BadVersionError,
    CoordError,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    NotLeaderError,
    Op,
)
from manatee_tpu.obs import bind_parent, bind_trace, get_span_store, \
    hlc_now, merge_remote
from manatee_tpu.obs.metrics import Histogram
from manatee_tpu.utils.logutil import setup_logging

log = logging.getLogger("manatee.coordd")

# server-side RPC handling latency (includes replication/fsync waits for
# mutations).  A standalone instrument, NOT the process registry: coordd
# renders its own builder under the "coordd" prefix.
_RPC_HANDLE = Histogram(
    "rpc_handle_duration_seconds",
    "server-side request handling latency (fsync+replication included "
    "for mutations)", ("op",))
# ops a client can legitimately name; anything else folds into "other"
# so a hostile/buggy client cannot explode label cardinality
_KNOWN_OPS = frozenset({
    "hello", "goodbye", "ping", "create", "get", "set", "delete",
    "exists", "children", "multi", "sync_status", "sync_hello",
    "sync_ack"})

_ERR_NAMES = {
    NoNodeError: "NoNodeError",
    NodeExistsError: "NodeExistsError",
    BadVersionError: "BadVersionError",
    NotEmptyError: "NotEmptyError",
}

MAX_LINE = 8 * 1024 * 1024
# per-connection outbound buffer cap; beyond this the subscriber is
# considered stalled and its connection is aborted (ADVICE r1)
MAX_BUFFERED = 16 * 1024 * 1024
# floor for client-requested disconnect_grace: must outlive the
# client's reconnect delay (plus connect/hello slack) or a transient
# TCP drop expires the session before the first resume attempt can
# happen.  Derived from the shared api constant so the two cannot drift.
MIN_DISCONNECT_GRACE = RECONNECT_DELAY + 0.15
# ops that change the persistent tree and must be replicated/quorum-gated
_MUTATING = frozenset({"create", "set", "delete", "multi"})


def parse_segment_name(p) -> tuple[int, int] | None:
    """(epoch, start_seq) from an op-log segment path, or None when
    the name is unrecognizable (startup deletes those as stale).
    Shared with `manatee-adm doctor` (manatee_tpu/doctor.py) so the
    on-disk naming contract cannot drift between writer and
    verifier."""
    parts = p.stem.split("-")
    try:
        return int(parts[-2][1:]), int(parts[-1])
    except (ValueError, IndexError):
        return None


def snapshot_shape_ok(snap) -> bool:
    """The loadable-snapshot shape contract ({v:1, root, seq, epoch})
    — shared with `manatee-adm doctor` for the same no-drift reason.
    seq/epoch are load-bearing: a snapshot missing them would default
    the epoch to 0 and delete the real-epoch segments as stale."""
    return (isinstance(snap, dict) and snap.get("v") == 1
            and "root" in snap and "seq" in snap and "epoch" in snap)


def encode_frame(msg: dict) -> bytes:
    """One wire frame (newline-delimited JSON).  The hot fan-out paths
    (watch fires, replication ships, leader pings) encode a message
    ONCE with this and hand the same bytes to every subscriber
    connection instead of re-serializing per connection.  Every
    outbound frame carries the server's HLC stamp (obs/causal.py):
    clients merge it, so a reaction to a watch fire or a reply sorts
    after the server-side work that produced it at any clock skew.
    Fan-out frames share one stamp — still a valid send event."""
    return (json.dumps({**msg, "hlc": hlc_now()}) + "\n").encode()


def _b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def _unb64(s: str | None) -> bytes:
    return base64.b64decode(s) if s else b""


def _wire_of(req: dict) -> dict:
    """The replayable projection of a persistent mutation request — the
    one format shared by the replication stream and the op log, so a
    follower's log and the leader's log replay identically."""
    return {k: req[k] for k in ("op", "path", "data", "version",
                                "sequential", "ops") if k in req}


def _seed_seq_counters(tree: model.ZNodeTree, req: dict,
                       expect) -> None:
    """Before replaying a logged sequential create, force its parent's
    counter to reproduce the ACKED name.  Necessary because ephemeral
    sequential creates (election joins) bump the same per-parent
    counter but are never logged — replay without seeding would mint a
    lower-numbered name than the one the client was acked and holds."""
    pairs = []
    if req.get("op") == "create" and req.get("sequential") \
            and isinstance(expect, str):
        pairs.append(expect)
    elif req.get("op") == "multi" and isinstance(expect, list):
        for o, e in zip(req.get("ops", []), expect):
            if o.get("kind") == "create" and o.get("sequential") \
                    and isinstance(e, str):
                pairs.append(e)
    for acked_path in pairs:
        suffix = acked_path[-10:]
        if not suffix.isdigit():
            continue
        parent_path = acked_path.rsplit("/", 1)[0] or "/"
        try:
            parent = tree._resolve(parent_path)
        except CoordError:
            continue        # parent created later in this very multi
        parent.seq_counter = max(parent.seq_counter, int(suffix))


def _apply_wire_op(tree: model.ZNodeTree, r: dict):
    """Apply one wire-format persistent mutation to *tree* (no session:
    ephemerals never ride this path).  Used by followers applying the
    leader's stream and by op-log replay at startup."""
    op = r.get("op")
    if op == "create":
        return tree.create(r["path"], _unb64(r.get("data")),
                           sequential=bool(r.get("sequential")))
    if op == "set":
        return tree.set(r["path"], _unb64(r.get("data")),
                        int(r.get("version", -1)))
    if op == "delete":
        tree.delete(r["path"], int(r.get("version", -1)))
        return None
    if op == "multi":
        ops = [Op(kind=o["kind"], path=o["path"],
                  data=_unb64(o.get("data")),
                  version=int(o.get("version", -1)),
                  ephemeral=False,
                  sequential=bool(o.get("sequential")))
               for o in r.get("ops", [])]
        return tree.multi(ops, session_id=None)
    raise CoordError("unknown replicated op: %r" % op)


class _Conn:
    def __init__(self, server: "CoordServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.session: model.Session | None = None
        self.alive = True
        self.is_follower = False
        self.follower_id: int | None = None
        # True while a request from this connection is being
        # dispatched: requests are served serially per connection, so
        # a mutation awaiting its replication fault budget blocks the
        # client's queued heartbeats — that silence is OURS, not the
        # client's, and must not heartbeat-expire its session
        self.in_dispatch = False
        # seq the follower's attach snapshot covered: ops at or below
        # it must not be re-shipped (the follower would see them as
        # gaps).  They count toward commit quorum only once the
        # follower has ACKED the attach snapshot as persisted
        # (attach_acked) — until then it may not even have received it
        self.attached_seq = -1
        self.attach_acked = False
        # seq -> ship waiters.  A LIST per seq: two concurrent ships
        # can legitimately share one seq (a mixed-transaction snapshot
        # pair captured after a concurrent op landed carries that op's
        # seq), and a dict of bare futures would drop the first
        self.ack_waiters: dict[int, list[asyncio.Future]] = {}
        # Coalesced outbound path: frames queue here and ONE flush per
        # event-loop tick writes them with a single writer.write — a
        # mutation that fires K watches on this connection (or a burst
        # of replies) costs one syscall, not K.  The slow-subscriber
        # sever is keyed on the PRE-EXISTING backlog (what the peer has
        # failed to drain), never on the frame being pushed — a single
        # frame larger than the bound (an attach snapshot for a big
        # tree) on a healthy connection must always be deliverable, as
        # it was on the uncoalesced path.
        self._outq: list[bytes] = []
        self._outq_bytes = 0
        self._flush_scheduled = False

    def push(self, msg: dict) -> None:
        self.push_bytes(encode_frame(msg))

    def push_bytes(self, data: bytes) -> None:
        """Queue one pre-encoded frame; fan-out callers encode once and
        pass the same bytes to every subscriber's push_bytes."""
        if not self.alive:
            return
        if self._outq_bytes > self.server.max_buffered:
            # frames already queued this tick exceed the bound without
            # being drained: don't let the in-process queue grow
            # unboundedly either (the new frame is NOT counted — it
            # must be allowed to be the one oversized frame)
            self.sever()
            return
        self._outq.append(data)
        self._outq_bytes += len(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if not self.alive or not self._outq:
            self._outq.clear()
            self._outq_bytes = 0
            return
        try:
            buffered = self.writer.transport.get_write_buffer_size()
        except (AttributeError, RuntimeError):
            buffered = 0
        if buffered > self.server.max_buffered:
            # slow/stalled subscriber: the transport still holds more
            # than the bound from PREVIOUS ticks.  Sever it, as
            # ZooKeeper does with slow clients; its session lives on
            # until the timeout, so a healthy client reconnects.
            self.sever()
            return
        data = b"".join(self._outq)
        self._outq.clear()
        self._outq_bytes = 0
        try:
            self.writer.write(data)
        except (ConnectionError, RuntimeError):
            self.alive = False

    def sever(self) -> None:
        """Kill the connection immediately (session untouched)."""
        self.alive = False
        try:
            self.writer.transport.abort()
        except (AttributeError, RuntimeError):
            self.writer.close()

    def watch_sink(self, kind: str):
        def sink(event):
            # the frame is encoded ONCE per (event, kind) no matter how
            # many connections subscribed — see CoordServer._watch_frame
            self.push_bytes(self.server._watch_frame(kind, event))
        sink.__owner__ = self
        return sink


class CoordServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 tick: float = 0.25, data_dir: str | None = None,
                 ensemble: list[tuple[str, int]] | None = None,
                 ensemble_id: int = 0, promote_grace: float = 2.0,
                 metrics_port: int | None = None, fsync: bool = True,
                 snapshot_every: int = 100_000):
        """*data_dir*: when set, every persistent mutation is fsynced to
        an op log there BEFORE it is acknowledged, with a periodic
        whole-tree snapshot as the compaction artifact (see the module
        docstring).  Ephemeral nodes do not survive a restart — their
        sessions are gone, and clients observe expiry and re-register.

        *fsync=False* trades crash durability for latency (dev only:
        an acked write can vanish in a power loss, the failure mode
        VERDICT r4 #1 calls a split-brain seed).  *snapshot_every*:
        logged ops between compactions (ZooKeeper's snapCount default;
        a 64 MB log-size bound triggers compaction too).

        *ensemble*: full member address list (including this server);
        *ensemble_id* is this server's index into it.  See the module
        docstring for the replication/leadership protocol."""
        self.host = host
        self.port = port
        self.tick = tick
        self.max_buffered = MAX_BUFFERED
        self.data_dir = data_dir
        self.ensemble = ensemble
        self.my_id = ensemble_id
        self.promote_grace = promote_grace
        self.role = "follower" if ensemble else "leader"
        self.leader_addr: tuple[str, int] | None = None
        self._seq = 0
        # last seq actually PUSHED to followers: pings must advertise
        # this, not self._seq — a mutation awaiting its log fsync has
        # bumped self._seq but not shipped yet, and a ping carrying
        # that unshipped seq would make every follower conclude it
        # drifted and resync (cancelling in-flight acks)
        self._shipped_seq = 0
        self._follower_conns: set[_Conn] = set()
        self._reap_tasks: set[asyncio.Task] = set()
        self._follow_task: asyncio.Task | None = None
        self._probe_task: asyncio.Task | None = None
        self._stopping = False
        self.fsync = fsync
        # stagger both compaction thresholds per member: ensemble
        # members log the same seqs and bytes, so an unstaggered bound
        # would make every member compact at the same instant — at a
        # large tree that means simultaneous walk stalls and missed
        # acks cluster-wide
        self.snapshot_every = int(snapshot_every) \
            + ensemble_id * max(1, int(snapshot_every) // 20)
        self.snapshot_bytes = self.SNAPSHOT_BYTES \
            + ensemble_id * (self.SNAPSHOT_BYTES // 20)
        self._oplog_fh = None
        self._oplog_bytes = 0    # bytes written to the current segment
        self._log_count = 0      # entries in the current segment
        self._synced_upto = 0    # bytes of it known fsynced
        self._log_gen = 0        # bumped on rotation
        self._fsync_task: asyncio.Task | None = None
        self._snap_seq = 0       # seq the on-disk snapshot covers
        # Epoch: bumped whenever the tree is REPLACED rather than
        # mutated (resync from the leader) — it tags log segments so
        # recovery can never replay a pre-resync segment on top of the
        # adopted tree (the crash-between-install-and-unlink window).
        self._persist_epoch = 0
        # a failed append that the synchronous-snapshot fallback could
        # not repair: refuse all further mutations rather than ack
        # writes whose durability is a lie
        self._wal_broken = False
        # serializes whole-log-superseding persists: two concurrent
        # mixed transactions must not race their epoch bumps, or one
        # could ack on the strength of a snapshot that later fails
        self._persist_lock = asyncio.Lock()
        # orders op-log appends (entries must hit the file in seq
        # order even though write+fsync run off the loop) and fences
        # them against segment rotation — without this fence, an
        # append during a superseding persist's epoch bump could land
        # in a new-epoch segment that recovery deletes as stale if the
        # crash comes before the snapshot installs (acked-write loss)
        self._log_lock = asyncio.Lock()
        self._compact_task: asyncio.Task | None = None
        self.tree = self._load_tree()
        self._server: asyncio.AbstractServer | None = None
        self._expiry_task: asyncio.Task | None = None
        self._conns: set[_Conn] = set()
        # session id -> live conn (one at a time)
        self._session_conns: dict[str, _Conn] = {}
        self.metrics_port = metrics_port
        self._metrics_runner = None
        self._mutations = 0
        # serialize-once watch fan-out: one-entry memo keyed on the
        # identity of the WatchEvent the tree is currently firing (all
        # K subscriber sinks for one mutation run consecutively), plus
        # a counter tests/operators can pin the guarantee on
        self._watch_memo: tuple | None = None
        self._watch_encodes = 0
        self._wire_tree(self.tree)

    def _watch_frame(self, kind: str, event) -> bytes:
        """The wire frame for one watch fire, encoded exactly once per
        (event, kind) and shared by every subscribed connection.  The
        memo keys on the event OBJECT: ZNodeTree._fire builds one event
        and calls all sinks for it synchronously, so a single entry is
        exact — a mutation touching K watchers serializes once."""
        memo = self._watch_memo
        if memo is not None and memo[0] is event and memo[1] == kind:
            return memo[2]
        data = encode_frame({"watch": {"kind": kind,
                                       "type": event.type.value,
                                       "path": event.path}})
        self._watch_memo = (event, kind, data)
        self._watch_encodes += 1
        return data

    def _wire_tree(self, tree: model.ZNodeTree) -> None:
        """One on_mutate hook per tree: count mutations (for /metrics).
        Persistence does NOT hang off this hook — durable writes happen
        at the ack points (_log_append / _persist_snapshot_now), and
        ephemeral-only mutations need no persistence at all."""
        def on_mutate():
            self._mutations += 1
        tree.on_mutate = on_mutate

    # ---- persistence: fsynced op-log segments + snapshot compaction ----
    #
    # ZooKeeper's layout: an append-only transaction log (here: numbered
    # JSONL segments, a new one per compaction) plus periodic whole-tree
    # snapshots.  The ack path pays ONLY the O(op) append+fsync; the
    # O(tree) snapshot runs rarely (snapshot_every ops or
    # SNAPSHOT_BYTES of log, ZK snapCount-style), with serialization
    # and disk I/O in a worker thread so a large history cannot stall
    # the event loop (a stalled follower misses acks and gets severed).

    SNAPSHOT_BYTES = 64 * 1024 * 1024

    def _snapshot_path(self):
        return Path(self.data_dir) / "coordd-tree.json"

    def _segment_path(self, start_seq: int):
        return Path(self.data_dir) / (
            "coordd-oplog-e%08d-%016d.jsonl"
            % (self._persist_epoch, start_seq))

    def _segments(self, *, epoch: int | None = None) -> list:
        """Log segment paths for *epoch* (default: the current one),
        oldest first."""
        want = self._persist_epoch if epoch is None else epoch
        out = []
        for p in Path(self.data_dir).glob("coordd-oplog-*.jsonl"):
            key = parse_segment_name(p)
            if key is not None and key[0] == want:
                out.append((key[1], p))
        out.sort()
        return [p for _s, p in out]

    def _stale_files(self) -> list:
        """Segments from other epochs (superseded by a resync snapshot)
        and orphaned snapshot tmp files — safe to delete."""
        out = []
        for p in Path(self.data_dir).glob("coordd-oplog-*.jsonl"):
            key = parse_segment_name(p)
            if key is None or key[0] != self._persist_epoch:
                out.append(p)
        out.extend(Path(self.data_dir).glob("coordd-tree.json.tmp*"))
        return out

    def _load_tree(self) -> model.ZNodeTree:
        if not self.data_dir:
            return model.ZNodeTree()
        Path(self.data_dir).mkdir(parents=True, exist_ok=True)
        path = self._snapshot_path()
        tree = model.ZNodeTree()
        if path.exists():
            try:
                snap = json.loads(path.read_text())
                if not snapshot_shape_ok(snap):
                    # from_snapshot is lenient (it returns an EMPTY
                    # tree for an unrecognized shape — right for wire
                    # adoption, catastrophic here: an empty tree with
                    # epoch 0 deletes the log segments as stale).
                    # seq/epoch are load-bearing for the same reason —
                    # a v1+root snapshot MISSING them would default
                    # the epoch to 0 and delete the real-epoch
                    # segments as stale
                    raise ValueError("unrecognized snapshot shape")
                tree = model.ZNodeTree.from_snapshot(snap)
                self._seq = int(snap.get("seq", 0))
                self._persist_epoch = int(snap.get("epoch", 0))
                log.info("loaded coordination tree from %s (seq %d, "
                         "epoch %d)", path, self._seq,
                         self._persist_epoch)
            except Exception as e:
                # ANY malformation — bad JSON/IO (ValueError/OSError)
                # or valid JSON of the wrong shape (KeyError/TypeError
                # out of from_snapshot).  Starting empty here would
                # reset the epoch to 0 and DELETE the log segments
                # (the one artifact an operator could recover from) as
                # stale — refuse instead, like any other
                # acked-write-losing malformation
                raise RuntimeError(
                    "tree snapshot %s exists but cannot be loaded "
                    "(%s); refusing to start — restore the member or "
                    "remove its data dir to resync it from the "
                    "ensemble" % (path, e))
        self._snap_seq = self._seq
        self._replay_oplog(tree)
        # crash leftovers: segments a resync snapshot superseded, and
        # snapshot tmp files a cancelled compaction never installed
        for p in self._stale_files():
            try:
                p.unlink()
            except OSError:
                pass
        return tree

    def _replay_oplog(self, tree: model.ZNodeTree) -> None:
        """Recovery: apply logged ops beyond the snapshot's seq, in
        segment order (current epoch only — a pre-resync segment must
        never replay on top of the adopted tree).  A torn final line of
        the final segment (crash mid-append) was never acked and is
        discarded.  ANY other malformation — mid-log corruption, a seq
        gap, a failed apply — means acked writes would be silently
        rolled back, so the server refuses to start (ZooKeeper's CRC'd
        log makes the same call): the operator restores the member or
        resyncs it from the ensemble."""
        segments = self._segments()
        replayed = 0
        for path in segments:
            raw = path.read_bytes()
            parts = raw.split(b"\n")
            # byte offset of each (possibly empty) part, for truncation
            offsets, pos = [], 0
            for part in parts:
                offsets.append(pos)
                pos += len(part) + 1
            nonempty = [j for j, part in enumerate(parts) if part]
            for i, j in enumerate(nonempty):
                line = parts[j]
                try:
                    ent = json.loads(line)
                    seq = int(ent["seq"])
                    req = ent["req"]
                except (ValueError, KeyError, TypeError):
                    if path is segments[-1] and i == len(nonempty) - 1:
                        # crash mid-append: discard AND truncate the
                        # torn bytes, or the next append (which reuses
                        # this very file when seqs line up) would
                        # concatenate a good entry onto them, turning
                        # an unacked torn tail into acked-write-eating
                        # corruption on the restart after that
                        log.warning("op log %s ends in a torn line; "
                                    "truncating it (it was never "
                                    "acked)", path.name)
                        os.truncate(path, offsets[j])
                        break
                    raise RuntimeError(
                        "op log %s is corrupt mid-stream (line %d): "
                        "acked writes would be lost; refusing to "
                        "start" % (path.name, i + 1))
                if seq <= self._seq:
                    continue        # superseded by the snapshot
                if seq != self._seq + 1:
                    raise RuntimeError(
                        "op log gap: entry seq %d after %d in %s; "
                        "acked writes would be lost; refusing to "
                        "start" % (seq, self._seq, path.name))
                expect = ent.get("expect")
                try:
                    _seed_seq_counters(tree, req, expect)
                    got = _apply_wire_op(tree, req)
                except CoordError as e:
                    raise RuntimeError(
                        "op log replay failed at seq %d in %s (%s); "
                        "refusing to start" % (seq, path.name, e))
                if "expect" in ent and got != expect:
                    raise RuntimeError(
                        "op log replay diverged at seq %d in %s: "
                        "produced %r, acked %r; refusing to start"
                        % (seq, path.name, got, expect))
                self._seq = seq
                replayed += 1
        if replayed:
            log.info("replayed %d op-log entries (now at seq %d)",
                     replayed, self._seq)

    def _fsync_data_dir(self) -> None:
        """Make a rename/create in data_dir itself durable."""
        if not self.fsync:
            return
        try:
            fd = os.open(self.data_dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    async def _log_append(self, seq: int, wire: dict,
                          expect=None) -> None:
        """THE durability point: one JSONL entry, written and fsynced
        before the caller acknowledges anything (leader → client,
        follower → leader).  O(op), independent of tree size.

        The buffered write runs in the loop (ordered: there is no
        await between the caller's seq assignment and the write), then
        the caller awaits a GROUP fsync in a worker thread — one
        fsync covers every entry queued while the previous one ran,
        so a slow disk neither stalls the event loop nor serializes
        throughput to one-op-per-fsync (ZooKeeper's sync-thread
        batching).  *expect* is the acked result, stored so replay can
        verify (and, for sequential creates, reproduce) exactly what
        was acknowledged.

        A failed append may have left a partial line — a silent gap
        that would poison every LATER fsynced entry at replay (replay
        stops at the gap).  The fallback is a synchronous snapshot,
        which re-covers this seq and supersedes the damaged segment;
        if even that fails, the server marks persistence broken and
        refuses further mutations rather than ack writes whose
        durability is a lie."""
        if not self.data_dir:
            return
        async with self._log_lock:
            line = (json.dumps({"seq": seq, "req": wire,
                                "expect": expect}) + "\n").encode()
            try:
                # error:OSError here injects a failed disk write at THE
                # durability point, exercising the synchronous-snapshot
                # fallback and the refuse-writes-when-broken contract
                await faults.point("coordd.oplog.append")
                if self._oplog_fh is None:
                    path = self._segment_path(seq)

                    # worker thread (under _log_lock, so append order
                    # is preserved): segment open + size probe are
                    # rotation-rare and must not stall the loop on a
                    # slow disk
                    def _open_segment(p=path):
                        fh = open(p, "ab")
                        return fh, os.fstat(fh.fileno()).st_size

                    self._oplog_fh, self._oplog_bytes = \
                        await asyncio.to_thread(_open_segment)
                    self._log_count = 0
                    self._synced_upto = self._oplog_bytes
                    self._fsync_data_dir()
                self._oplog_fh.write(line)
                self._oplog_fh.flush()
            except (OSError, ValueError) as e:
                self._append_failed(seq, e)
                return
            self._oplog_bytes += len(line)
            self._log_count += 1
            gen, target = self._log_gen, self._oplog_bytes
            if self._log_count >= self.snapshot_every \
                    or self._oplog_bytes >= self.snapshot_bytes:
                self._request_compaction()
        if self.fsync:
            try:
                await self._log_fsync(gen, target)
            except (OSError, ValueError) as e:
                self._append_failed(seq, e)

    def _append_failed(self, seq: int, e: Exception) -> None:
        log.error("op-log append failed at seq %d (%s); falling back "
                  "to a synchronous snapshot", seq, e)
        if self._persist_snapshot_now():
            return
        self._wal_broken = True
        raise CoordError("cannot persist mutation; refusing writes "
                         "until restart") from None

    async def _log_fsync(self, gen: int, target: int) -> None:
        """Group commit: wait until the current segment is fsynced at
        least to byte *target*.  Concurrent callers share in-flight
        fsyncs; whoever finds none running starts one.  A generation
        change means the segment was rotated — which only happens
        after a quiesce (async paths) or a fsynced superseding
        snapshot (sync paths), so our entry is durable either way."""
        while self._log_gen == gen and self._synced_upto < target:
            t = self._fsync_task
            if t is None or t.done():
                self._fsync_task = t = asyncio.create_task(
                    self._fsync_once())
            try:
                await t
            except (OSError, ValueError):
                if self._log_gen == gen:
                    raise      # genuine disk failure on OUR segment
                # a synchronous rotation (snapshot fallback/shutdown)
                # closed the fh under the fsync; the superseding
                # snapshot covers every entry we were waiting on
                return

    async def _fsync_once(self) -> None:
        fh = self._oplog_fh
        if fh is None:
            return
        gen = self._log_gen
        target = self._oplog_bytes
        await asyncio.get_running_loop().run_in_executor(
            None, os.fsync, fh.fileno())
        if self._log_gen == gen:
            # a SYNCHRONOUS rotation (append-failure fallback) may have
            # swapped the segment under this fsync; crediting its byte
            # target to the NEW segment would ack unsynced entries
            self._synced_upto = max(self._synced_upto, target)

    async def _quiesce_log(self) -> None:
        """Under _log_lock: fsync everything written to the current
        segment so rotation cannot strand flushed-but-unsynced entries
        whose callers have been told (via gen change) they are safe."""
        if self.fsync and self._oplog_fh is not None:
            await self._log_fsync(self._log_gen, self._oplog_bytes)

    def _rotate_segment(self) -> None:
        """Close the current segment; the next append opens a fresh
        one.  Cheap, runs at compaction start so appends made while the
        snapshot is being written land in a segment it does not cover.
        Callers on async paths quiesce the group fsync first."""
        if self._oplog_fh is not None:
            self._oplog_fh.close()
            self._oplog_fh = None
        self._log_gen += 1
        self._log_count = 0
        self._oplog_bytes = 0
        self._synced_upto = 0

    def _request_compaction(self) -> None:
        # only ever called from _log_append (a coroutine), so a
        # running loop is guaranteed
        if self._compact_task is None or self._compact_task.done():
            self._compact_task = asyncio.create_task(self._compact())

    async def _compact(self) -> None:
        """Write a snapshot covering everything logged so far, then drop
        the covered segments.  Only the tree walk runs in the loop;
        serialization + write + fsync run in a worker thread."""
        await asyncio.sleep(0.05)          # debounce bursts
        async with self._log_lock:
            # the fence + quiesce guarantee every logged entry is
            # fsynced before its segment becomes compaction-covered,
            # and that the walk sees every logged mutation
            await self._quiesce_log()
            self._rotate_segment()
            covered = self._segments()
            epoch = self._persist_epoch
            seq = self._seq
            snap = self.tree.to_snapshot()
            snap["seq"] = seq
            snap["epoch"] = epoch
        loop = asyncio.get_running_loop()
        try:
            tmp = await loop.run_in_executor(
                None, self._write_snapshot_tmp, snap)
        except OSError as e:
            log.error("compaction snapshot failed: %s", e)
            return
        self._install_snapshot(tmp, seq, covered, epoch)

    def _write_snapshot_tmp(self, snap: dict):
        path = self._snapshot_path()
        tmp = path.with_name("%s.tmp-%d-%d"
                             % (path.name, snap["epoch"], snap["seq"]))
        with open(tmp, "w") as f:
            f.write(json.dumps(snap))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        return tmp

    def _install_snapshot(self, tmp, seq: int, covered: list,
                          epoch: int, *, force: bool = False) -> bool:
        """Rename a written snapshot into place and drop the segments
        it covers.  If the world moved on while it was being written (a
        forced resync adopted a different tree, or a newer snapshot
        landed), it is stale and discarded — which still counts as
        success for the caller's mutation: whatever superseded it
        covers at least as much.  Returns False only on I/O failure."""
        if not force and (epoch != self._persist_epoch
                          or seq < self._snap_seq):
            try:
                tmp.unlink()
            except OSError:
                pass
            return True
        try:
            tmp.replace(self._snapshot_path())
        except OSError as e:
            log.error("cannot install snapshot: %s", e)
            return False
        self._fsync_data_dir()
        self._snap_seq = seq
        for p in covered:
            try:
                p.unlink()
            except OSError:
                pass
        self._fsync_data_dir()
        return True

    def _snapshot_prep(self) -> dict:
        """Start a whole-log-superseding snapshot: bump the epoch (so
        pre-existing segments and any in-flight compaction of the old
        tree are dead on arrival) and capture a consistent view."""
        self._persist_epoch += 1
        self._rotate_segment()
        snap = self.tree.to_snapshot()
        snap["seq"] = self._seq
        snap["epoch"] = self._persist_epoch
        return snap

    def _persist_snapshot_now(self) -> bool:
        """Synchronous fsynced snapshot superseding the whole log — the
        O(tree)-on-the-loop path, kept for non-async contexts (clean
        shutdown, append-failure fallback, tests without a loop)."""
        if not self.data_dir:
            return True
        snap = self._snapshot_prep()
        covered = self._stale_files()
        try:
            tmp = self._write_snapshot_tmp(snap)
        except OSError as e:
            log.error("cannot persist tree snapshot: %s", e)
            return False
        return self._install_snapshot(tmp, self._seq, covered,
                                      self._persist_epoch, force=True)

    async def _persist_snapshot_async(self) -> tuple | None:
        """The same whole-log-superseding snapshot with serialization +
        write + fsync in a worker thread — used on ack paths (mixed
        transactions, follower resync) so a large tree cannot stall the
        event loop and sever the rest of the ensemble.  Serialized via
        _persist_lock; returns the (seq, snapshot) pair captured under
        the locks — a CONFIRMED-installed consistent view an ack or a
        replication ship may ride on — or None when the persist failed.
        Callers that replicate the snapshot must ship THIS pair:
        re-reading self._seq/tree after the await could pair this
        mutation's ship with a concurrent later op's seq, colliding
        with that op's own sync_op on the followers."""
        if not self.data_dir:
            # no persistence configured: the consistent pair is still
            # what replication callers need (no await between the two
            # reads, so they are atomic in the event loop — the
            # atomic-section annotation makes mnt-lint enforce that)
            # mnt-lint: atomic-section=seq-snapshot-pair
            snap = self.tree.to_snapshot()
            snap["seq"] = self._seq
            return (self._seq, snap)
            # mnt-lint: end-atomic-section
        async with self._persist_lock, self._log_lock:
            # BOTH locks for the whole prep→write→install span: the
            # epoch has been bumped but the new-epoch snapshot is not
            # installed yet, so an append slipping in now would land in
            # a new-epoch segment that recovery deletes as stale if we
            # crash before the install — acked-write loss.  The log
            # lock keeps appends out until the install completes.
            await self._quiesce_log()
            snap = self._snapshot_prep()
            covered = self._stale_files()
            epoch = self._persist_epoch
            loop = asyncio.get_running_loop()
            try:
                tmp = await loop.run_in_executor(
                    None, self._write_snapshot_tmp, snap)
            except OSError as e:
                log.error("cannot persist tree snapshot: %s", e)
                return None
            if epoch != self._persist_epoch:
                # superseded while writing by a SYNCHRONOUS persist
                # (async ones serialize on the lock).  It has already
                # completed — so _snap_seq tells us whether it actually
                # installed something covering our seq; only that
                # justifies success on an ack path.
                try:
                    tmp.unlink()
                except OSError:
                    pass
                if self._snap_seq >= snap["seq"]:
                    return (snap["seq"], snap)
                return None
            if self._install_snapshot(tmp, snap["seq"], covered,
                                      epoch, force=True):
                return (snap["seq"], snap)
            return None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_LINE)
        self.port = self._server.sockets[0].getsockname()[1]
        store = get_span_store()
        if store.peer is None:
            # identify this member's dispatch spans in a fetched tree;
            # never clobber an identity set by an embedding process
            store.peer = "coordd:%s:%d" % (self.host, self.port)
        self._expiry_task = asyncio.create_task(self._expiry_loop())
        if self.ensemble:
            self._follow_task = asyncio.create_task(self._follow_loop())
        if self.metrics_port is not None:
            await self._start_metrics()
        log.info("coordd listening on %s:%d%s%s", self.host, self.port,
                 " (persistent: %s)" % self.data_dir
                 if self.data_dir else "",
                 " (ensemble id %d of %d)" % (self.my_id, len(self.ensemble))
                 if self.ensemble else "")

    async def stop(self) -> None:
        self._stopping = True
        if self._metrics_runner is not None:
            await self._metrics_runner.cleanup()
            self._metrics_runner = None
        for t in (self._follow_task, self._probe_task,
                  self._expiry_task, self._compact_task):
            if t:
                t.cancel()
        for t in list(self._reap_tasks):
            t.cancel()
        # reap before the final synchronous compaction: a half-dead
        # compact task must not race _persist_snapshot_now for the
        # segment files, and loop tasks must be done unwinding before
        # connections are severed under them
        await asyncio.gather(
            *(t for t in (self._follow_task, self._probe_task,
                          self._expiry_task, self._compact_task) if t),
            *list(self._reap_tasks), return_exceptions=True)
        self._persist_snapshot_now()   # final compaction (rotates too)
        # close live connections BEFORE wait_closed(): since 3.12 it waits
        # for every connection handler to finish
        for conn in list(self._conns):
            conn.sever()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # ---- metrics (beyond-parity observability; ZooKeeper exposes the
    # equivalent via four-letter words / its own Prometheus provider) ----

    async def _start_metrics(self) -> None:
        from aiohttp import web

        from manatee_tpu.daemons.common import attach_obs_routes

        async def metrics(_req):
            return web.Response(text=self._render_metrics(),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/metrics", metrics)
        # the shared introspection table — /events, /spans, /history,
        # /alerts, /profile, /tasks, /faults (daemons/common.py)
        attach_obs_routes(app)
        self._metrics_runner = web.AppRunner(app)
        await self._metrics_runner.setup()
        site = web.TCPSite(self._metrics_runner, self.host,
                           self.metrics_port)
        await site.start()
        if self.metrics_port == 0:
            self.metrics_port = self._metrics_runner.addresses[0][1]
        log.info("coordd metrics on %s:%d", self.host, self.metrics_port)

    def _render_metrics(self) -> str:
        from manatee_tpu.utils.prom import MetricsBuilder

        b = MetricsBuilder("coordd")
        b.metric("role", "gauge", "this member's current role",
                 [('{role="%s"}' % r, 1 if r == self.role else 0)
                  for r in ("leader", "follower")])
        # a gauge, not a counter: followers jump to the leader's seq on
        # resync and an ex-leader's seq can move backwards when it
        # force-syncs to the incumbent — operators compare seqs ACROSS
        # members, not rates
        b.metric("seq", "gauge",
                 "replication sequence position", self._seq)
        b.metric("mutations_total", "counter",
                 "tree mutations applied by this member",
                 self._mutations)
        b.metric("sessions", "gauge", "live client sessions",
                 sum(1 for s in self.tree.sessions.values()
                     if not s.expired))
        b.metric("connections", "gauge", "open client connections",
                 len(self._conns))
        b.metric("followers_connected", "gauge",
                 "follower members attached (leader only)",
                 len(self._follower_conns))
        if self.ensemble:
            if self.role == "leader":
                # only the leader commits, so only it has a quorum fact;
                # followers omit the series rather than export a
                # permanently-alarming 0
                need = self._quorum_needed()
                have = 1 + len(self._follower_conns)
                b.metric("quorum_ok", "gauge",
                         "1 when this leader can commit mutations",
                         1 if (need is None or have >= need) else 0)
            b.metric("ensemble_size", "gauge",
                     "configured member count", len(self.ensemble))

        # incremental gauge maintained by ZNodeTree on mutate: scrape
        # cost must not scale with tree size (the old implementation
        # walked the whole tree here, per scrape)
        b.metric("znodes", "gauge", "nodes in the tree (incl. root)",
                 self.tree.node_count)
        b.metric("watches", "gauge", "registered one-shot watches",
                 sum(len(v) for v in self.tree._watches.values()))
        b.metric("watch_serializations_total", "counter",
                 "watch events serialized for fan-out (one per fired "
                 "event, however many connections subscribe)",
                 self._watch_encodes)
        b.histogram(_RPC_HANDLE.name, _RPC_HANDLE.help,
                    _RPC_HANDLE.buckets, _RPC_HANDLE.series())
        from manatee_tpu.obs.metrics import _fmt
        from manatee_tpu.obs.process import (
            process_instruments,
            refresh_process_metrics,
        )
        from manatee_tpu.utils.prom import label_str
        refresh_process_metrics()
        for inst in process_instruments():
            b.metric(inst.name, inst.kind, inst.help,
                     [(label_str(**labels), _fmt(v))
                      for labels, v in inst.samples()])
        return b.render()

    def _expire_due_sessions(self) -> None:
        for sid in self.tree.expired_sessions():
            conn = self._session_conns.get(sid)
            if conn is not None and conn.in_dispatch:
                # the client is silent because WE are: its request is
                # mid-dispatch (e.g. a mutation waiting out the
                # replication fault budget) and its queued heartbeats
                # sit unread behind it.  Expiring a live client here
                # would delete its election ephemeral and trigger a
                # spurious failover; refresh it instead — its queued
                # pings take over as soon as the dispatch returns.
                self.tree.touch_session(sid)
                continue
            log.info("session %s expired", sid)
            self.tree.expire_session(sid)
            self.tree.sessions.pop(sid, None)
            self._session_conns.pop(sid, None)
            if conn is not None:
                # hung-but-connected client: sever the socket so it
                # observes expiry instead of lingering half-alive
                conn.sever()

    async def _expiry_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick)
            self._expire_due_sessions()

    # ---- per-connection ----

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ConnectionError, ValueError):
                    # ValueError = line over the stream limit
                    break
                if not line:
                    break
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    conn.push({"ok": False, "error": "CoordError",
                               "msg": "bad json"})
                    continue
                conn.in_dispatch = True
                # fold the client's piggybacked HLC in BEFORE dispatch
                # so everything this request causes (oplog append,
                # watch fires, journal records) stamps after the
                # client's send; degrades to wall-clock ordering on
                # any merge failure, never fails the request
                await merge_remote(req.get("hlc"))
                tid = req.get("trace")
                sid = req.get("span")
                t0 = time.monotonic()
                t0_wall = time.time()
                try:
                    # bind the client's trace AND span ids so every log
                    # line this request produces correlates with the
                    # transition that caused it (the sitter's state
                    # write), and the server-side handling span parents
                    # under the CALLER's span (a sibling of the
                    # client-side coord.rpc record, whose id is minted
                    # post-hoc and never on the wire)
                    with bind_trace(tid if isinstance(tid, str)
                                    else None), \
                            bind_parent(sid if isinstance(sid, str)
                                        else None):
                        await self._dispatch(conn, req)
                finally:
                    conn.in_dispatch = False
                    op = req.get("op")
                    known = (op if isinstance(op, str)
                             and op in _KNOWN_OPS else "other")
                    dur = time.monotonic() - t0
                    _RPC_HANDLE.observe(dur, op=known)
                    if known not in ("ping", "other") \
                            and isinstance(sid, str):
                        # only traced, span-carrying requests (the
                        # sitters' state writes and reads): heartbeats
                        # and anonymous probes are waterfall noise
                        get_span_store().record(
                            "coordd.handle", ts=t0_wall, dur=dur,
                            op=known,
                            trace_id=tid if isinstance(tid, str)
                            else None,
                            parent_id=sid)
                try:
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    break
        finally:
            conn.alive = False
            self._conns.discard(conn)
            self._follower_conns.discard(conn)
            for futs in conn.ack_waiters.values():
                for fut in futs:
                    if not fut.done():
                        fut.cancel()
            # the session survives the connection; watches don't
            self.tree.remove_watches_for(
                lambda w: getattr(w, "__owner__", None) is conn)
            if conn.session and not conn.session.expired \
                    and self._session_conns.get(conn.session.id) is conn:
                # only if the session wasn't already resumed elsewhere
                del self._session_conns[conn.session.id]
                conn.session.connected = False
                conn.session.last_seen = time.monotonic()
                conn.session.disconnected_at = conn.session.last_seen
                if conn.session.disconnect_grace is not None:
                    # precise fast-path expiry: don't leave the grace
                    # quantized by the periodic tick (a failover waits
                    # on this deadline)
                    asyncio.get_running_loop().call_later(
                        conn.session.disconnect_grace + 0.005,
                        self._expire_due_sessions)
            writer.close()

    async def _dispatch(self, conn: _Conn, req: dict) -> None:
        xid = req.get("xid")
        op = req.get("op")
        try:
            # server-side black hole: the request is consumed but never
            # answered — the client's frame hangs like a dropped packet
            if await faults.point("coordd.dispatch") == "drop":
                return
            if op == "sync_ack":
                # follower ack of a replicated op/snapshot: resolve the
                # waiters, no reply (acks must not generate traffic).
                # Acks are CUMULATIVE: ships and acks ride one FIFO
                # stream with persist-before-ack, so an ack at S proves
                # a state covering every seq <= S is on the follower's
                # disk — resolve all of them (a ship's own ack can
                # arrive after a superseding one already proved it)
                seq = int(req.get("seq", -1))
                for s in [s for s in conn.ack_waiters if s <= seq]:
                    for fut in conn.ack_waiters.pop(s):
                        if not fut.done():
                            fut.set_result(True)
                if conn.is_follower and seq >= conn.attached_seq:
                    # the attach snapshot (or something after it) is
                    # durably on the follower's disk: its attach seq
                    # may now count toward commit quorums
                    conn.attach_acked = True
                return
            if op == "hello":
                result = self._op_hello(conn, req)
            elif op == "sync_status":
                result = self._op_sync_status()
            elif op == "sync_hello":
                result = self._op_sync_hello(conn, req)
            elif conn.session is None or conn.session.expired:
                raise CoordError("no session (hello first)")
            else:
                self.tree.touch_session(conn.session.id)
                mutating = op in _MUTATING
                mode = None
                if mutating:
                    if self._wal_broken:
                        raise CoordError(
                            "persistence broken (earlier disk "
                            "failure); refusing writes until restart")
                    self._check_quorum()
                    # classify BEFORE applying: an ephemeral delete
                    # target is gone afterwards
                    mode = self._replication_mode(op, req)
                result = self._op(conn, op, req)
                if mutating and mode is not None:
                    self._seq += 1
                    # capture OUR seq now: the awaits below yield to
                    # concurrent dispatches that bump self._seq further
                    seq = self._seq
                    # durability BEFORE the ack (and before replication,
                    # so an acked write is never on followers' disks but
                    # not ours): fsync the op to our log, or — for the
                    # rare mixed transaction the log cannot replay
                    # against a session-less tree — the full snapshot
                    if mode == "op":
                        await self._log_append(seq, _wire_of(req),
                                               result)
                        acks = await self._replicate_op(seq, req,
                                                        result)
                    elif not self.data_dir \
                            and not self._follower_conns:
                        # memory-only standalone: nothing to persist,
                        # nobody to ship to — skip the O(tree)
                        # snapshot walk entirely (a follower attaching
                        # right after this check gets the mutation via
                        # its attach snapshot)
                        acks = 0
                    else:
                        pair = await self._persist_snapshot_async()
                        if pair is None:
                            self._wal_broken = True
                            raise CoordError(
                                "cannot persist mutation; refusing "
                                "writes until restart")
                        acks = await self._replicate_snapshot(*pair)
                    self._check_commit_quorum(acks)
            conn.push({"xid": xid, "ok": True, "result": result})
        except asyncio.CancelledError:
            raise           # server teardown mid-op: unwind, no reply
        except NotLeaderError as e:
            reply = {"xid": xid, "ok": False, "error": "NotLeaderError",
                     "msg": str(e)}
            if self.leader_addr is not None:
                reply["leader"] = "%s:%d" % self.leader_addr
            conn.push(reply)
        except CoordError as e:
            conn.push({"xid": xid, "ok": False,
                       "error": _ERR_NAMES.get(type(e), "CoordError"),
                       "msg": str(e)})
        except Exception as e:
            # malformed-but-valid-JSON requests must get an error reply,
            # not kill the connection
            log.warning("bad request %r: %s", op, e)
            conn.push({"xid": xid, "ok": False, "error": "CoordError",
                       "msg": "bad request: %s" % e})

    def _op_hello(self, conn: _Conn, req: dict):
        if self.ensemble and self.role != "leader":
            raise NotLeaderError("member %d is not the leader" % self.my_id)
        sid = req.get("session_id")
        if sid:
            sess = self.tree.sessions.get(sid)
            if not sess or sess.expired:
                raise CoordError("session expired: %s" % sid)
            old = self._session_conns.get(sid)
            if old and old is not conn:
                old.sever()
        else:
            # Floor: a timeout at or below the ping interval would
            # perpetually expire healthy sessions now that connected
            # sessions are subject to heartbeat expiry (ZK likewise
            # clamps to a server-side minimum of 2 ticks).
            timeout = max(float(req.get("session_timeout", 60.0)),
                          4 * self.tick)
            grace = req.get("disconnect_grace")
            if grace is not None:
                # must outlive the expiry tick and the client's
                # reconnect delay, or a transient drop could never be
                # resumed before the fast path expires it
                grace = max(float(grace), 2 * self.tick,
                            MIN_DISCONNECT_GRACE)
            sess = self.tree.create_session(timeout,
                                            disconnect_grace=grace)
        sess.connected = True
        sess.last_seen = time.monotonic()
        sess.disconnected_at = None
        conn.session = sess
        self._session_conns[sess.id] = conn
        # report the EFFECTIVE (possibly floored) values so the client
        # can reason from what the server will actually enforce
        return {"session_id": sess.id, "session_timeout": sess.timeout,
                "disconnect_grace": sess.disconnect_grace}

    def _op(self, conn: _Conn, op: str, req: dict):
        tree = self.tree
        path = req.get("path", "")
        if op == "ping":
            return "pong"
        if op == "goodbye":
            # explicit session end: ephemerals vanish NOW, like closing a
            # ZooKeeper handle (and like MemoryCoord.close()).  Without
            # this a cleanly-shut-down peer lingers in the election until
            # its session times out.
            sid = conn.session.id
            tree.expire_session(sid)
            tree.sessions.pop(sid, None)
            self._session_conns.pop(sid, None)
            return "bye"
        if op == "create":
            return tree.create(
                path, _unb64(req.get("data")),
                ephemeral_owner=(conn.session.id if req.get("ephemeral")
                                 else None),
                sequential=bool(req.get("sequential")))
        if op == "get":
            data, version = tree.get(path)
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            return {"data": _b64(data), "version": version,
                    "ctime": stat.ctime if stat else 0.0}
        if op == "set":
            return tree.set(path, _unb64(req.get("data")),
                            int(req.get("version", -1)))
        if op == "delete":
            tree.delete(path, int(req.get("version", -1)))
            return None
        if op == "exists":
            if req.get("watch"):
                tree.add_watch(model.DATA, path, conn.watch_sink(model.DATA))
            stat = tree.exists(path)
            if stat is None:
                return None
            return {"version": stat.version,
                    "ephemeral_owner": stat.ephemeral_owner,
                    "num_children": stat.num_children,
                    "ctime": stat.ctime}
        if op == "children":
            names = tree.get_children(path)
            if req.get("watch"):
                tree.add_watch(model.CHILDREN, path,
                               conn.watch_sink(model.CHILDREN))
            return names
        if op == "multi":
            ops = []
            for o in req.get("ops", []):
                ops.append(Op(
                    kind=o["kind"], path=o["path"],
                    data=_unb64(o.get("data")),
                    version=int(o.get("version", -1)),
                    ephemeral=bool(o.get("ephemeral")),
                    sequential=bool(o.get("sequential"))))
            return tree.multi(ops, session_id=conn.session.id)
        raise CoordError("unknown op: %r" % op)

    # ---- ensemble: leader side ----

    def _op_sync_status(self) -> dict:
        return {"role": self.role, "seq": self._seq, "id": self.my_id,
                "leader": ("%s:%d" % self.leader_addr
                           if self.leader_addr else None)}

    def _op_sync_hello(self, conn: _Conn, req: dict) -> dict:
        if self.role != "leader":
            raise NotLeaderError("member %d is not the leader" % self.my_id)
        fid = req.get("id")
        # dedupe by member id: a resyncing follower's stale half-dead
        # connection must not keep counting toward quorum
        for old in list(self._follower_conns):
            if old.follower_id == fid and old is not conn:
                self._follower_conns.discard(old)
                old.sever()
        conn.is_follower = True
        conn.follower_id = fid
        conn.attached_seq = self._seq
        self._follower_conns.add(conn)
        log.info("follower %s joined (seq %d)", fid, self._seq)
        snap = self.tree.to_snapshot()
        return {"seq": self._seq, "snapshot": snap}

    def _quorum_needed(self) -> int | None:
        """Members (incl. self) that must hold a write, or None when no
        quorum applies (standalone, or a 2-member ensemble — which has
        no safe quorum smaller than itself; there we prioritize
        availability and document the tradeoff)."""
        if not self.ensemble or len(self.ensemble) < 3:
            return None
        return len(self.ensemble) // 2 + 1

    def _check_quorum(self) -> None:
        """Cheap pre-check: refuse mutations outright when not even a
        majority of followers is connected."""
        need = self._quorum_needed()
        if need is not None and 1 + len(self._follower_conns) < need:
            raise CoordError(
                "no quorum: %d of %d ensemble members connected"
                % (1 + len(self._follower_conns), len(self.ensemble)))

    def _check_commit_quorum(self, acks: int) -> None:
        """Post-replication check: an acked client write must exist on a
        majority, or a partitioned minority leader could acknowledge
        writes the eventual winner never saw.  The op is already applied
        locally; refusing here makes the failure AMBIGUOUS to the client
        (as in ZooKeeper connection loss) rather than silently lossy."""
        need = self._quorum_needed()
        if need is not None and 1 + acks < need:
            raise CoordError(
                "no quorum: write replicated to %d of %d members "
                "(uncommitted; retry may see it applied)"
                % (1 + acks, len(self.ensemble)))

    def _replication_mode(self, op: str, req: dict) -> str | None:
        """How a mutation reaches followers: 'op' (ship the op itself),
        'snapshot' (rare fallback), or None (no persistent effect —
        ephemerals live only on the leader, so there is nothing to
        ship; election joins/leaves stay O(0) for the ensemble).

        Unshipped ephemeral-sequential creates mean the counter of a
        parent like election/ runs ahead on the leader; that is safe:
        the counter only names EPHEMERAL children, which die with their
        sessions at failover, so a promoted follower's lower counter
        cannot collide with anything still alive."""
        if op == "create":
            return None if req.get("ephemeral") else "op"
        if op in ("set", "delete"):
            stat = self.tree.exists(req.get("path", ""))
            if stat is not None and stat.ephemeral_owner is not None:
                return None
            return "op"
        if op == "multi":
            # our transactions (putClusterState) are persistent-only; a
            # transaction that CREATES an ephemeral, or sets/deletes an
            # existing one, has effects followers must not (create) or
            # cannot (set/delete a node they do not hold) apply — fall
            # back to the full snapshot, which carries exactly the
            # persistent outcome
            for o in req.get("ops", []):
                if o.get("ephemeral"):
                    return "snapshot"
                if o.get("kind") in ("set", "delete"):
                    stat = self.tree.exists(o.get("path", ""))
                    if stat is not None and \
                            stat.ephemeral_owner is not None:
                        return "snapshot"
            return "op"
        return "op"

    async def _replicate_op(self, seq: int, req: dict, result) -> int:
        """Ship one persistent mutation as the op itself — O(op), not
        O(tree).  *seq* is the mutation's own seq, captured at its
        bump (self._seq may have moved on while the caller awaited the
        log fsync).  *result* rides along so followers can verify
        their apply produced the same outcome (sequential names,
        versions)."""
        return await self._ship(
            {"sync_op": {"seq": seq, "req": _wire_of(req),
                         "expect": result}}, seq)

    async def _replicate_snapshot(self, seq: int, snap: dict) -> int:
        """Ship the full persistent tree (the rare mixed-transaction
        fallback).  Ships the SAME (seq, snapshot) pair the persist
        captured under the locks: re-reading self._seq/tree here — the
        persist await yields to concurrent dispatches — could pair
        this mutation's ship with a LATER op's seq, which would collide
        with that op's own sync_op ship (duplicate seq on the stream)
        and read as a gap on every follower."""
        return await self._ship(
            {"sync": {"seq": seq, "snapshot": snap}}, seq)

    async def _ship(self, msg: dict, seq: int) -> int:
        """Push *msg* (carrying the current seq) to every follower and
        collect acks.  Returns as soon as enough followers for a commit
        quorum have acked — a hung follower must not add its full fault
        budget to every client write (a SIGSTOPped member once cost
        every putClusterState, takeovers included, up to 1s here).
        Laggards keep the rest of the fault budget in the background and
        are severed if still silent (they resync with a fresh
        sync_hello).  Returns the number of followers acked so far."""
        self._shipped_seq = max(self._shipped_seq, seq)
        if not self._follower_conns:
            return 0
        # one serialization for the whole follower set (a 5-member
        # ensemble used to pay 4 json.dumps of the same ship)
        frame = encode_frame(msg)
        loop = asyncio.get_running_loop()
        waiters: list[tuple[_Conn, asyncio.Future]] = []
        acks = 0
        attach_pending = set()
        for f in list(self._follower_conns):
            if f.attached_seq >= seq:
                # its attach snapshot already carried this op, so
                # re-shipping would read as a gap on its side.  It
                # counts toward the quorum only once it has ACKED that
                # snapshot as persisted — before that it may not have
                # received a byte of it.
                if f.attach_acked:
                    acks += 1
                    continue
                # attach in flight: push nothing, but DO register a
                # waiter — the cumulative sync_ack for the attach
                # snapshot (seq >= attached_seq >= our seq) resolves
                # it.  Skipping instead failed writes issued in the
                # attach window with a spurious no-quorum (e.g. right
                # after a blackout restart, both followers mid-attach)
                # and silently dropped 2-member wait-for-all semantics.
                attach_pending.add(f)
                fut = loop.create_future()
                f.ack_waiters.setdefault(seq, []).append(fut)
                waiters.append((f, fut))
                continue
            fut = loop.create_future()
            f.ack_waiters.setdefault(seq, []).append(fut)
            f.push_bytes(frame)
            waiters.append((f, fut))
        need = self._quorum_needed()
        # followers needed beyond ourselves; no-quorum ensembles (2
        # members) keep wait-for-all semantics — there is no safe
        # subset to commit on
        need_f = acks + len(waiters) if need is None \
            else min(need - 1, acks + len(waiters))
        # the fault budget scales with tick (the reference's analogue is
        # ZooKeeper's tick-derived timeouts), floored so a slow-but-live
        # follower on a loaded host is not severed spuriously
        deadline = loop.time() + max(4 * self.tick, 1.0)
        pending = {fut for _f, fut in waiters}
        while pending and acks < need_f:
            done, pending = await asyncio.wait(
                pending, timeout=max(0.0, deadline - loop.time()),
                return_when=asyncio.FIRST_COMPLETED)
            if not done:
                break                      # deadline hit
            acks += sum(1 for d in done if not d.cancelled())
            if acks >= need_f:
                break
        # attach-pending conns were never pushed this ship: a slow
        # big-tree attach must not be severed as a laggard here (its
        # own stream timeouts catch a dead attach); its waiter simply
        # resolves on the eventual attach ack or is cancelled with the
        # connection
        laggards = [(f, fut) for f, fut in waiters
                    if not fut.done() and f not in attach_pending]
        if laggards:
            # strong refs: the loop holds tasks weakly and a GC'd
            # reaper would leave hung followers connected forever
            t = asyncio.create_task(
                self._reap_laggards(seq, laggards, deadline))
            self._reap_tasks.add(t)
            t.add_done_callback(self._reap_tasks.discard)
        return acks

    async def _reap_laggards(self, seq: int,
                             waiters: list, deadline: float) -> None:
        """Give not-yet-acked followers the remainder of the fault
        budget off the write path, then sever the still-silent ones."""
        loop = asyncio.get_running_loop()
        remaining = deadline - loop.time()
        if remaining > 0:
            await asyncio.wait([fut for _f, fut in waiters],
                               timeout=remaining)
        for f, fut in waiters:
            if not fut.done():
                futs = f.ack_waiters.get(seq)
                if futs is not None:
                    try:
                        futs.remove(fut)
                    except ValueError:
                        pass
                    if not futs:
                        del f.ack_waiters[seq]
                log.warning("follower not acking seq %d; severing", seq)
                self._follower_conns.discard(f)
                f.sever()

    async def _leader_probe_loop(self) -> None:
        """Leader heartbeat to followers + dual-leader resolution after a
        partition heal: the leader with (higher seq, then lower id) wins;
        the other steps down."""
        interval = max(self.tick * 2, 0.5)
        while not self._stopping and self.role == "leader":
            await asyncio.sleep(interval)
            ping = encode_frame(
                # advertise the last SHIPPED seq: self._seq may be
                # ahead of the stream while a mutation awaits its log
                # fsync, and an unshipped seq would read as drift
                {"sync_ping": {"seq": self._shipped_seq}})
            for f in list(self._follower_conns):
                f.push_bytes(ping)
            # probe the other members CONCURRENTLY: sequential 0.5s
            # probe timeouts against unreachable members would stretch
            # the gap between sync_pings past the followers' idle
            # timeout (max(2s, 6*tick)), making healthy followers
            # resync-flap exactly when the ensemble is degraded
            others = [(idx, addr)
                      for idx, addr in enumerate(self.ensemble)
                      if idx != self.my_id]
            results = await asyncio.gather(
                *(self._probe(addr) for _i, addr in others),
                return_exceptions=True)
            for (idx, _addr), st in zip(others, results):
                if isinstance(st, BaseException):
                    # a malformed/hostile reply must not kill the
                    # heartbeat loop (followers would idle-timeout and
                    # resync-flap forever) — but it IS a bug signal:
                    # sync_status swallows all anticipated failures
                    log.warning("probe of member %d raised %r", idx, st)
                    continue
                if st and st.get("role") == "leader":
                    if (st.get("seq", 0) > self._seq
                            or (st.get("seq", 0) == self._seq
                                and idx < self.my_id)):
                        self._step_down("dual leader: member %d seq %s wins"
                                        % (idx, st.get("seq")))
                        break

    def _become_leader(self) -> None:
        log.warning("promoting to ensemble leader (id %d, seq %d)",
                    self.my_id, self._seq)
        self.role = "leader"
        self._shipped_seq = self._seq
        self.leader_addr = self.ensemble[self.my_id]
        if self._probe_task is None or self._probe_task.done():
            self._probe_task = asyncio.create_task(
                self._leader_probe_loop())

    def _step_down(self, why: str) -> None:
        log.warning("stepping down from leader: %s", why)
        self.role = "follower"
        self.leader_addr = None
        # sessions (and their ephemerals) die with leadership: clients
        # observe expiry and re-register on the winning leader
        for sid in list(self.tree.sessions):
            self.tree.expire_session(sid)
        self.tree.sessions.clear()
        self._session_conns.clear()
        self._follower_conns.clear()
        for conn in list(self._conns):
            conn.sever()
        if self._follow_task is None or self._follow_task.done():
            self._follow_task = asyncio.create_task(self._follow_loop())

    # ---- ensemble: follower side ----

    async def _probe(self, addr: tuple[str, int]) -> dict | None:
        """One-shot sync_status request to another member; None if it
        does not answer promptly."""
        from manatee_tpu.coord.client import sync_status
        return await sync_status(addr[0], addr[1], 0.5)

    async def _follow_loop(self) -> None:
        """Find and follow the leader; promote when, for promote_grace,
        a QUORUM of members is reachable and none of them outranks us.
        Rank is (seq, then lowest id): a member with a newer persisted
        tree must win or its committed writes would be rolled back;
        among equals the lowest id wins.  A reachable outranking
        non-leader resets the clock — it is deciding too and will
        promote.

        The quorum-contact requirement is what makes election safe
        against the double fault ZooKeeper also excludes: a
        majority-acked write lives on ≥ quorum members, any two quorums
        intersect, so a candidate that contacted a quorum and outranks
        all of it cannot be missing an acked write — a laggard that can
        only see a minority never self-promotes, no matter how long the
        up-to-date members stay unreachable."""
        interval = max(self.tick, 0.2)
        need = self._quorum_needed()
        unranked_since: float | None = None
        while not self._stopping and self.role != "leader":
            leader: tuple[str, int] | None = None
            outranked = False
            reachable = 1                     # self
            for idx, addr in enumerate(self.ensemble):
                if idx == self.my_id:
                    continue
                st = await self._probe(addr)
                if st is None:
                    continue
                reachable += 1
                if st.get("role") == "leader":
                    leader = addr
                    break
                peer_seq = int(st.get("seq", 0))
                if peer_seq > self._seq or \
                        (peer_seq == self._seq and idx < self.my_id):
                    outranked = True
            if leader is not None:
                unranked_since = None
                try:
                    await self._follow(leader)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.info("follow of %s:%d ended: %s",
                             leader[0], leader[1], e)
                # fall through to the sleep: a fast-failing follow must
                # not busy-loop full-snapshot resyncs against the leader
            elif outranked or (need is not None and reachable < need):
                unranked_since = None
            else:
                now = time.monotonic()
                if unranked_since is None:
                    unranked_since = now
                elif now - unranked_since >= self.promote_grace:
                    self._become_leader()
                    return
            await asyncio.sleep(interval)

    async def _follow(self, addr: tuple[str, int]) -> None:
        """Stream snapshots from the leader until the connection dies or
        we are no longer a follower."""
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(addr[0], addr[1], limit=MAX_LINE), 1.0)
        try:
            writer.write((json.dumps(
                {"op": "sync_hello", "xid": 0,
                 "id": self.my_id, "seq": self._seq}) + "\n").encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), 2.0)
            msg = json.loads(line)
            if not msg.get("ok"):
                raise CoordError("sync_hello refused: %s" % msg.get("msg"))
            res = msg["result"]

            async def ack(seq: int) -> None:
                writer.write((json.dumps(
                    {"op": "sync_ack", "seq": seq}) + "\n").encode())
                await writer.drain()

            # the full resync is authoritative: adopt the leader's tree
            # even if our (possibly divergent) seq is higher, or we
            # would livelock re-resyncing forever
            if not await self._apply_sync(int(res["seq"]),
                                          res["snapshot"], force=True):
                raise CoordError("cannot persist resynced tree")
            # the attach snapshot is now durably ours: ack it, so the
            # leader may count our attached_seq toward commit quorums
            await ack(int(res["seq"]))
            self.leader_addr = addr
            log.info("following leader %s:%d (seq %d)",
                     addr[0], addr[1], self._seq)
            # leader pings every probe interval; silence means it is
            # gone (or wedged) and we must re-elect
            idle = max(2.0, 6 * self.tick)
            while not self._stopping and self.role == "follower":
                line = await asyncio.wait_for(reader.readline(), idle)
                if not line:
                    break
                msg = json.loads(line)
                if "sync" in msg:
                    s = msg["sync"]
                    seq = int(s["seq"])
                    if seq <= self._seq:
                        # concurrent dispatches on the leader can ship
                        # a mixed-transaction snapshot pair CAPTURED
                        # before ops this stream already delivered; our
                        # state supersedes it (same leader, in-order
                        # stream — mid-stream our seq only advances via
                        # these ships) and everything up to our seq is
                        # already fsynced, so the ack is honest.  Never
                        # regress the tree onto it.
                        await ack(seq)
                        continue
                    # _apply_sync persists (fsynced) before we ack: a
                    # majority-acked write must be on a majority of
                    # DISKS, not page caches — no persist, no ack
                    if not await self._apply_sync(seq, s["snapshot"]):
                        break
                    await ack(seq)
                elif "sync_op" in msg:
                    s = msg["sync_op"]
                    seq = int(s["seq"])
                    wire = s.get("req")
                    if wire and seq <= self._seq:
                        # already covered: a concurrent mixed
                        # transaction's snapshot ship on this stream
                        # carried this op's effect (its pair seq can
                        # land at or past ours) and we persisted it —
                        # ack-and-skip instead of reading it as a gap
                        # and resyncing a healthy stream
                        await ack(seq)
                        continue
                    if seq != self._seq + 1 or not wire:
                        # gap or malformed ship: never apply-and-log a
                        # bad entry (it would poison our durable log);
                        # resync with a fresh sync_hello
                        break
                    try:
                        got = self._apply_op(wire)
                    except CoordError as e:
                        log.warning("replicated op failed (diverged?): "
                                    "%s; resyncing", e)
                        break
                    if s.get("expect", got) != got:
                        log.warning("replicated op result %r != leader's "
                                    "%r; resyncing", got, s.get("expect"))
                        break
                    self._seq = seq
                    # fsync our log BEFORE acking the leader — our ack
                    # is what lets it count us toward the commit quorum
                    await self._log_append(seq, wire, got)
                    await ack(seq)
                elif "sync_ping" in msg:
                    # a HIGHER advertised seq means we missed data:
                    # resync.  A lower one is normal — we may have
                    # attached (sync_hello) ahead of what the leader
                    # has shipped on the stream; divergence in that
                    # direction is caught by the next sync_op apply.
                    if int(msg["sync_ping"].get("seq", -1)) > self._seq:
                        break   # drifted; resync with a fresh sync_hello
        finally:
            self.leader_addr = None
            try:
                writer.close()
            except RuntimeError:
                pass

    def _apply_op(self, r: dict):
        """Apply one leader-replicated persistent mutation to the local
        tree.  Followers hold only the persistent tree: no sessions, no
        ephemerals, no client watches.  Version checks run against OUR
        tree — a BadVersionError here means we diverged from the leader
        and the caller falls back to a full resync."""
        return _apply_wire_op(self.tree, r)

    async def _apply_sync(self, seq: int, snap: dict, *,
                          force: bool = False) -> bool:
        """Adopt a leader-shipped tree and persist it durably (worker
        thread for the serialization+fsync).  Returns False when the
        persist failed — the caller must NOT ack: an ack claims the
        write is on our disk."""
        if seq < self._seq and not force:
            # a ship from the past means we diverged ahead of the
            # leader: never ack it — resync instead
            return False
        tree = model.ZNodeTree.from_snapshot(snap)
        self.tree = tree
        self._seq = seq
        self._wire_tree(tree)
        if not self.data_dir:
            # memory-only member: nothing to persist, and the pair the
            # no-data_dir persist branch would build is for replication
            # callers — an O(tree) walk this path would just discard
            return True
        # the adopted tree supersedes whatever snapshot+log we held:
        # persist it (fsynced, epoch-bumped) BEFORE the ack — the old
        # log must never replay on top of the new snapshot
        return await self._persist_snapshot_async() is not None


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description="manatee coordination daemon")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2281)
    p.add_argument("--data-dir", default=None,
                   help="persist the tree here (survives restarts): "
                        "fsynced op log + compaction snapshots")
    p.add_argument("--no-fsync", action="store_true",
                   help="skip fsync on the op log/snapshot (dev only: "
                        "acked writes may vanish on power loss)")
    p.add_argument("--snapshot-every", type=int, default=100_000,
                   help="logged ops between compaction snapshots "
                        "(ZooKeeper snapCount parity)")
    p.add_argument("--tick", type=float, default=0.25,
                   help="session-expiry scan interval (seconds)")
    p.add_argument("--ensemble", default=None,
                   help="full member list 'h1:p1,h2:p2,...' incl. this "
                        "server (replicated mode)")
    p.add_argument("--ensemble-id", type=int, default=0,
                   help="this server's index into --ensemble")
    p.add_argument("--promote-grace", type=float, default=2.0,
                   help="seconds of lower-member unreachability before a "
                        "follower promotes itself")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus /metrics on this port "
                        "(default: disabled)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    setup_logging("manatee-coordd", args.verbose)

    ensemble = None
    if args.ensemble:
        from manatee_tpu.coord.client import parse_connstr
        ensemble = parse_connstr(args.ensemble)

    async def run():
        from manatee_tpu.daemons.common import start_daemon_introspection

        # the always-on profiling plane; the metrics listener serves
        # its /profile and /tasks when --metrics-port is given
        intro = start_daemon_introspection(None)
        server = CoordServer(args.host, args.port, tick=args.tick,
                             data_dir=args.data_dir,
                             ensemble=ensemble,
                             ensemble_id=args.ensemble_id,
                             promote_grace=args.promote_grace,
                             metrics_port=args.metrics_port,
                             fsync=not args.no_fsync,
                             snapshot_every=args.snapshot_every)
        await server.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.stop()
        await intro.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
