"""NetCoord — TCP client for coordd.

Session semantics mirror what the reference's ZK client gives
lib/zookeeperMgr.js: the session survives TCP disconnects; the client
auto-reconnects and resumes it.  If the session cannot be resumed before
it times out, a single 'expired' event fires and the client is dead —
the layer above builds a fresh client (ConsensusMgr._setup_client, after
lib/zookeeperMgr.js:560-570).

Watch delivery across reconnects: armed one-shot watches are refired
synthetically after a resume (the handler re-reads and re-arms, so a
spurious event is harmless while a missed one would wedge the cluster).
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import logging
import time
from typing import Callable

from manatee_tpu.coord.api import (
    BadVersionError,
    ConnectionLossError,
    CoordClient,
    CoordError,
    EventType,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    Op,
    SessionExpiredError,
    Stat,
    WatchCb,
    WatchEvent,
)

log = logging.getLogger("manatee.coord.client")

_ERRS = {
    "NoNodeError": NoNodeError,
    "NodeExistsError": NodeExistsError,
    "BadVersionError": BadVersionError,
    "NotEmptyError": NotEmptyError,
    "CoordError": CoordError,
}

RECONNECT_DELAY = 0.2
MAX_LINE = 8 * 1024 * 1024  # must match coordd's stream limit


class NetCoord(CoordClient):
    def __init__(self, host: str, port: int, *,
                 session_timeout: float = 60.0):
        self.host = host
        self.port = port
        self._timeout = session_timeout
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._session_id: str | None = None
        self._xids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[tuple[str, str], list[WatchCb]] = {}
        self._session_cbs: list[Callable[[str], None]] = []
        self._read_task: asyncio.Task | None = None
        self._ping_task: asyncio.Task | None = None
        self._reconnect_task: asyncio.Task | None = None
        self._closed = False
        self._expired = False
        self._connected = asyncio.Event()

    # ---- lifecycle ----

    async def connect(self) -> None:
        await self._open_conn(resume=False)

    async def _open_conn(self, resume: bool) -> None:
        reader, writer = await asyncio.open_connection(
            self.host, self.port, limit=MAX_LINE)
        self._reader, self._writer = reader, writer
        self._read_task = asyncio.ensure_future(self._read_loop(reader))
        hello: dict = {"op": "hello"}
        if resume and self._session_id:
            hello["session_id"] = self._session_id
        else:
            hello["session_timeout"] = self._timeout
        res = await self._request(hello)
        self._session_id = res["session_id"]
        # adopt the server's (possibly floored) timeout so our reconnect
        # give-up deadline matches the session's actual server lifetime
        self._timeout = float(res.get("session_timeout", self._timeout))
        self._connected.set()
        if self._ping_task is None or self._ping_task.done():
            self._ping_task = asyncio.ensure_future(self._ping_loop())
        self._notify("connected")

    async def close(self) -> None:
        self._closed = True
        for t in (self._read_task, self._ping_task, self._reconnect_task):
            if t:
                t.cancel()
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass
        self._fail_pending(ConnectionLossError("closed"))

    @property
    def session_id(self) -> str | None:
        return None if self._expired else self._session_id

    def on_session_event(self, cb: Callable[[str], None]) -> None:
        self._session_cbs.append(cb)

    def _notify(self, event: str) -> None:
        for cb in list(self._session_cbs):
            try:
                cb(event)
            except Exception:
                log.exception("session callback failed")

    # ---- wire ----

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                try:
                    line = await reader.readline()
                except ValueError:
                    break  # response over the stream limit
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "watch" in msg:
                    self._deliver_watch(msg["watch"])
                    continue
                fut = self._pending.pop(msg.get("xid"), None)
                if fut and not fut.done():
                    fut.set_result(msg)
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            if not self._closed:
                self._on_disconnect()

    def _on_disconnect(self) -> None:
        self._connected.clear()
        self._fail_pending(ConnectionLossError("connection lost"))
        if self._expired or self._closed:
            return
        self._notify("disconnected")
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.ensure_future(self._reconnect())

    async def _reconnect(self) -> None:
        deadline = time.monotonic() + self._timeout
        while not self._closed and time.monotonic() < deadline:
            await asyncio.sleep(RECONNECT_DELAY)
            try:
                await self._open_conn(resume=True)
            except (ConnectionLossError, OSError):
                continue         # transient: retry until deadline
            except CoordError:
                break            # server refused the session: expired
            self._refire_watches()
            return
        if not self._closed:
            self._expire()

    def _expire(self) -> None:
        if self._expired:
            return
        self._expired = True
        self._watches.clear()
        self._fail_pending(SessionExpiredError(self._session_id or "?"))
        self._notify("expired")

    def _fail_pending(self, err: Exception) -> None:
        for fut in list(self._pending.values()):
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    def _deliver_watch(self, w: dict) -> None:
        key = (w.get("kind"), w.get("path"))
        cbs = self._watches.pop(key, [])
        try:
            event = WatchEvent(EventType(w.get("type")), w.get("path"))
        except ValueError:
            return
        for cb in cbs:
            try:
                cb(event)
            except Exception:
                log.exception("watch callback failed")

    def _refire_watches(self) -> None:
        """After a session resume the server-side watches are gone; fire
        every armed watch so handlers re-read and re-arm."""
        armed = self._watches
        self._watches = {}
        for (kind, path), cbs in armed.items():
            ev = WatchEvent(EventType.DATA_CHANGED
                            if kind == "data" else EventType.CHILDREN_CHANGED,
                            path)
            for cb in cbs:
                try:
                    cb(ev)
                except Exception:
                    log.exception("watch refire failed")

    async def _ping_loop(self) -> None:
        interval = max(self._timeout / 3.0, 0.05)
        try:
            while not self._closed and not self._expired:
                await asyncio.sleep(interval)
                if not self._connected.is_set():
                    continue
                try:
                    await self._request({"op": "ping"})
                except CoordError:
                    pass
        except asyncio.CancelledError:
            pass

    async def _request(self, req: dict) -> dict | list | str | int | None:
        if self._expired:
            raise SessionExpiredError(self._session_id or "?")
        if self._writer is None or self._writer.is_closing():
            raise ConnectionLossError("not connected")
        xid = next(self._xids)
        req["xid"] = xid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        try:
            self._writer.write((json.dumps(req) + "\n").encode())
            await self._writer.drain()
        except (ConnectionError, RuntimeError) as e:
            self._pending.pop(xid, None)
            raise ConnectionLossError(str(e)) from None
        msg = await fut
        if msg.get("ok"):
            return msg.get("result")
        raise _ERRS.get(msg.get("error"), CoordError)(msg.get("msg", ""))

    # ---- ops ----

    def _arm(self, kind: str, path: str, watch: WatchCb | None) -> bool:
        if watch is None:
            return False
        self._watches.setdefault((kind, path), []).append(watch)
        return True

    def _disarm(self, kind: str, path: str, watch: WatchCb) -> None:
        """Error-path cleanup of a just-armed watch.  Tolerant: the entry
        may have been consumed concurrently by _deliver_watch /
        _refire_watches / session expiry, and raising here would mask
        the original CoordError."""
        cbs = self._watches.get((kind, path))
        if cbs and watch in cbs:
            cbs.remove(watch)

    async def create(self, path: str, data: bytes = b"", *,
                     ephemeral: bool = False,
                     sequential: bool = False) -> str:
        return await self._request({
            "op": "create", "path": path,
            "data": base64.b64encode(data).decode(),
            "ephemeral": ephemeral, "sequential": sequential})

    async def get(self, path: str, watch: WatchCb | None = None
                  ) -> tuple[bytes, int]:
        data, version, _ctime = await self.get_full(path, watch)
        return data, version

    async def get_full(self, path: str, watch: WatchCb | None = None
                       ) -> tuple[bytes, int, float]:
        """get() plus the node's creation time — one round trip."""
        armed = self._arm("data", path, watch)
        try:
            res = await self._request({"op": "get", "path": path,
                                       "watch": armed})
        except CoordError:
            if armed:
                self._disarm("data", path, watch)
            raise
        return (base64.b64decode(res["data"]), res["version"],
                res.get("ctime", 0.0))

    async def set(self, path: str, data: bytes, version: int = -1) -> int:
        return await self._request({
            "op": "set", "path": path,
            "data": base64.b64encode(data).decode(), "version": version})

    async def delete(self, path: str, version: int = -1) -> None:
        await self._request({"op": "delete", "path": path,
                             "version": version})

    async def exists(self, path: str, watch: WatchCb | None = None
                     ) -> Stat | None:
        armed = self._arm("data", path, watch)
        try:
            res = await self._request({"op": "exists", "path": path,
                                       "watch": armed})
        except CoordError:
            if armed:
                self._disarm("data", path, watch)
            raise
        if res is None:
            return None
        return Stat(version=res["version"],
                    ephemeral_owner=res.get("ephemeral_owner"),
                    num_children=res.get("num_children", 0),
                    ctime=res.get("ctime", 0.0))

    async def get_children(self, path: str, watch: WatchCb | None = None
                           ) -> list[str]:
        armed = self._arm("children", path, watch)
        try:
            return await self._request({"op": "children", "path": path,
                                        "watch": armed})
        except CoordError:
            if armed:
                self._disarm("children", path, watch)
            raise
    async def multi(self, ops: list[Op]) -> list:
        wire_ops = []
        for op in ops:
            wire_ops.append({
                "kind": op.kind, "path": op.path,
                "data": base64.b64encode(op.data or b"").decode(),
                "version": op.version,
                "ephemeral": op.ephemeral,
                "sequential": op.sequential,
            })
        return await self._request({"op": "multi", "ops": wire_ops})
