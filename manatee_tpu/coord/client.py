"""NetCoord — TCP client for coordd.

Session semantics mirror what the reference's ZK client gives
lib/zookeeperMgr.js: the session survives TCP disconnects; the client
auto-reconnects and resumes it.  If the session cannot be resumed before
it times out, a single 'expired' event fires and the client is dead —
the layer above builds a fresh client (ConsensusMgr._setup_client, after
lib/zookeeperMgr.js:560-570).

Watch delivery across reconnects: armed one-shot watches are refired
synthetically after a resume (the handler re-reads and re-arms, so a
spurious event is harmless while a missed one would wedge the cluster).
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import logging
import time
from typing import Callable

from manatee_tpu.coord.api import (
    RECONNECT_DELAY,
    BadVersionError,
    ConnectionLossError,
    CoordClient,
    CoordError,
    EventType,
    NodeExistsError,
    NoNodeError,
    NotEmptyError,
    NotLeaderError,
    Op,
    SessionExpiredError,
    Stat,
    WatchCb,
    WatchEvent,
)
from manatee_tpu import faults
from manatee_tpu.obs import (
    current_span_id,
    current_trace,
    get_journal,
    get_registry,
    get_span_store,
    hlc_now,
    merge_remote,
)
from manatee_tpu.utils.retry import Backoff

log = logging.getLogger("manatee.coord.client")

_REG = get_registry()
_RPC_DUR = _REG.histogram(
    "coord_rpc_duration_seconds",
    "coordination RPC round-trip latency", ("op",))
_SESSION_EVENTS = _REG.counter(
    "coord_session_events_total",
    "coordination session lifecycle events "
    "(connected/disconnected/expired)", ("event",))
# Amortization gauges: with the mux pool, N shards in one process show
# coord_connections/coord_sessions of 1 and coord_mux_handles of N —
# the before/after of fleet mode in one scrape (docs/performance.md).
_CONNECTIONS = _REG.gauge(
    "coord_connections",
    "open coordination TCP connections from this process")
_SESSIONS = _REG.gauge(
    "coord_sessions",
    "live coordination sessions owned by this process")
_MUX_HANDLES = _REG.gauge(
    "coord_mux_handles",
    "logical coordination handles multiplexed over this process's "
    "pooled connections")

_ERRS = {
    "NoNodeError": NoNodeError,
    "NodeExistsError": NodeExistsError,
    "BadVersionError": BadVersionError,
    "NotEmptyError": NotEmptyError,
    "NotLeaderError": NotLeaderError,
    "CoordError": CoordError,
}

HANDSHAKE_TIMEOUT = 5.0
MAX_LINE = 8 * 1024 * 1024  # must match coordd's stream limit


def _reply_deadline(session_timeout: float) -> float:
    """Client-side bound on any RPC reply.  A request whose reply never
    arrives — a one-way partition where our frames reach the server
    (keeping the session alive!) but its replies vanish — would
    otherwise pin the caller forever: the server sees heartbeats, so
    NEITHER side ever detects the partition.  ZooKeeper clients bound
    replies the same way.  Generous (never below 2x the handshake
    budget): false positives sever a healthy stream."""
    return max(session_timeout, 2 * HANDSHAKE_TIMEOUT)


def parse_connstr(connstr: str, default_port: int = 2281
                  ) -> list[tuple[str, int]]:
    """'h1:p1,h2:p2,h3' -> [(h1,p1),(h2,p2),(h3,default)] — the shape of
    the reference's zkCfg.connStr (etc/sitter.json)."""
    addrs: list[tuple[str, int]] = []
    for part in connstr.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.partition(":")
        addrs.append((host, int(port) if port else default_port))
    if not addrs:
        raise ValueError("empty connstr: %r" % connstr)
    return addrs


async def sync_status(host: str, port: int,
                      timeout: float = 1.0) -> dict | None:
    """One-shot sessionless status probe of a coordd member: {role, seq,
    id, leader} — None if it does not answer promptly.  Used by ensemble
    members for election probing and by `manatee-adm coord-status`."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        writer.write(b'{"op":"sync_status","xid":0}\n')
        await writer.drain()
        line = await asyncio.wait_for(reader.readline(), timeout)
        res = json.loads(line)
        # a malformed reply (e.g. literal null, a bare list) is 'does
        # not answer properly', not an exception for the caller
        if not isinstance(res, dict):
            return None
        result = res.get("result")
        return result if isinstance(result, dict) else None
    except (OSError, ValueError, asyncio.TimeoutError):
        return None
    finally:
        try:
            writer.close()
        except RuntimeError:
            pass


class NetCoord(CoordClient):
    def __init__(self, host: str, port: int | None = None, *,
                 session_timeout: float = 60.0,
                 disconnect_grace: float | None = None):
        """*host* is either a single hostname (with *port*) or a full
        comma-separated connection string 'h1:p1,h2:p2' covering a
        coordd ensemble (parity: zkCfg.connStr,
        /root/reference/etc/sitter.json).  The client rotates through
        the addresses on connect/reconnect and honors not-leader
        redirects from ensemble followers.

        *disconnect_grace* (opt-in fast crash detection): asks coordd to
        expire this session after that much post-disconnect silence
        instead of the full session timeout.  A SIGKILLed process FINs
        immediately, so failover detection drops from session_timeout to
        the grace; set it above the reconnect delay (0.2s) or a
        transient drop can expire the session before it can be
        resumed."""
        if port is None:
            self._addrs = parse_connstr(host)
        else:
            self._addrs = [(host, int(port))]
        self._addr_idx = 0
        self._timeout = session_timeout
        self._disconnect_grace = disconnect_grace
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._session_id: str | None = None
        self._xids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[tuple[str, str], list[WatchCb]] = {}
        self._session_cbs: list[Callable[[str], None]] = []
        self._read_task: asyncio.Task | None = None
        self._ping_task: asyncio.Task | None = None
        self._reconnect_task: asyncio.Task | None = None
        self._closed = False
        self._expired = False
        self._connected = asyncio.Event()
        # gauge bookkeeping (inc exactly once per live connection /
        # session, dec exactly once however it ends)
        self._conn_counted = False
        self._sess_counted = False

    # ---- lifecycle ----

    @property
    def host(self) -> str:
        return self._addrs[self._addr_idx][0]

    @property
    def port(self) -> int:
        return self._addrs[self._addr_idx][1]

    def _rotate(self, hint: str | None = None) -> None:
        """Advance to the next ensemble address — or jump straight to a
        leader address hinted by a follower's refusal."""
        if hint:
            h, _, p = hint.partition(":")
            try:
                addr = (h, int(p))
            except ValueError:
                addr = None
            if addr is not None:
                if addr not in self._addrs:
                    self._addrs.append(addr)
                self._addr_idx = self._addrs.index(addr)
                return
        self._addr_idx = (self._addr_idx + 1) % len(self._addrs)

    async def connect(self) -> None:
        last: Exception | None = None
        attempts = 0
        # bound re-evaluated each pass: a NotLeaderError redirect may
        # APPEND the hinted leader address, and it deserves a try too
        while attempts < len(self._addrs) + 1:
            attempts += 1
            try:
                await self._open_conn(resume=False)
                return
            except (OSError, CoordError) as e:
                last = e
        if isinstance(last, CoordError):
            raise last
        raise ConnectionLossError(str(last)) from last

    async def _open_conn(self, resume: bool) -> None:
        host, port = self._addrs[self._addr_idx]
        if await faults.point("coord.client.connect") == "drop":
            # black-holed SYN: indistinguishable from an unreachable
            # route — the partition primitive for (re)connects
            self._rotate()
            raise ConnectionLossError(
                "connect to %s:%d black-holed (fault)" % (host, port))
        try:
            # bounded: a SYN into a blackholed route would otherwise pin
            # the connect for kernel-retry minutes
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port, limit=MAX_LINE),
                HANDSHAKE_TIMEOUT)
        except asyncio.TimeoutError:
            self._rotate()
            raise ConnectionLossError(
                "connect to %s:%d timed out" % (host, port)) from None
        except OSError:
            self._rotate()
            raise
        # Handshake inline, before the read loop owns the stream: a
        # follower's not-leader refusal must rotate us without tripping
        # the disconnect/reconnect machinery.  No watch pushes can
        # arrive before the hello reply (no session yet).
        hello: dict = {"op": "hello", "xid": 0}
        if resume and self._session_id:
            hello["session_id"] = self._session_id
        else:
            hello["session_timeout"] = self._timeout
            if self._disconnect_grace is not None:
                hello["disconnect_grace"] = self._disconnect_grace
        try:
            writer.write((json.dumps(hello) + "\n").encode())
            await writer.drain()
            # bounded: a wedged-but-accepting member (SIGSTOP — the
            # kernel still completes accepts) must not pin us forever
            line = await asyncio.wait_for(reader.readline(), HANDSHAKE_TIMEOUT)
        except (ConnectionError, RuntimeError, OSError,
                asyncio.TimeoutError) as e:
            writer.close()
            self._rotate()
            raise ConnectionLossError("handshake: %s" % e) from None
        except BaseException:
            # a cancellation (session teardown racing the dial) landing
            # on the drain/readline awaits above must not strand the
            # half-handshaken socket: nothing else holds a reference to
            # it yet, so an unclosed leave here leaks the fd forever
            # (mnt-lint: cancel-unsafe-acquire)
            writer.close()
            raise
        if not line:
            writer.close()
            self._rotate()
            raise ConnectionLossError("handshake EOF")
        try:
            msg = json.loads(line)
        except json.JSONDecodeError:
            writer.close()
            self._rotate()
            raise CoordError("bad handshake reply")
        if not msg.get("ok"):
            writer.close()
            if msg.get("error") == "NotLeaderError":
                self._rotate(hint=msg.get("leader"))
                raise NotLeaderError(msg.get("msg", ""))
            raise _ERRS.get(msg.get("error"), CoordError)(msg.get("msg", ""))
        res = msg.get("result") or {}
        self._reader, self._writer = reader, writer
        self._read_task = asyncio.create_task(self._read_loop(reader))
        self._session_id = res["session_id"]
        # adopt the server's (possibly floored) values so our reconnect
        # give-up deadline — and anything reasoning about the effective
        # disconnect grace — matches what the server actually enforces
        self._timeout = float(res.get("session_timeout", self._timeout))
        if res.get("disconnect_grace") is not None:
            self._disconnect_grace = float(res["disconnect_grace"])
        self._connected.set()
        if not self._conn_counted:
            _CONNECTIONS.inc()
            self._conn_counted = True
        if not self._sess_counted:
            # a resume keeps the same session; only count it once
            _SESSIONS.inc()
            self._sess_counted = True
        if self._ping_task is None or self._ping_task.done():
            self._ping_task = asyncio.create_task(self._ping_loop())
        self._notify("connected")

    async def close(self) -> None:
        self._closed = True
        for t in (self._read_task, self._ping_task, self._reconnect_task):
            if t:
                t.cancel()
        # reap before touching the writer: the read loop's finally runs
        # to completion here, so no disconnect handling can interleave
        # with (or outlive) the teardown below
        await asyncio.gather(
            *(t for t in (self._read_task, self._ping_task,
                          self._reconnect_task) if t),
            return_exceptions=True)
        if self._writer:
            if not self._expired and self._connected.is_set():
                # best-effort explicit session end, so our ephemerals
                # vanish NOW instead of at session timeout — closing a
                # ZooKeeper handle ends the session, and
                # MemoryCoord.close() already matches that
                try:
                    self._writer.write(b'{"op":"goodbye","xid":0}\n')
                    await self._writer.drain()
                except (ConnectionError, RuntimeError, OSError):
                    pass
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass
        if self._conn_counted:
            _CONNECTIONS.dec()
            self._conn_counted = False
        if self._sess_counted:
            # a clean close ends the session (goodbye above)
            _SESSIONS.dec()
            self._sess_counted = False
        self._fail_pending(ConnectionLossError("closed"))

    @property
    def session_id(self) -> str | None:
        return None if self._expired else self._session_id

    def on_session_event(self, cb: Callable[[str], None]) -> None:
        self._session_cbs.append(cb)

    def _notify(self, event: str) -> None:
        _SESSION_EVENTS.inc(event=event)
        get_journal().record("coord.session." + event,
                             session=self._session_id,
                             addr="%s:%d" % (self.host, self.port))
        for cb in list(self._session_cbs):
            try:
                cb(event)
            except Exception:
                log.exception("session callback failed")

    # ---- wire ----

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                try:
                    line = await reader.readline()
                except ValueError:
                    break  # response over the stream limit
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if await faults.point("coord.client.recv") == "drop":
                    continue    # the frame vanished in flight
                # merge the server's piggybacked HLC before delivering
                # the frame: degrades to wall-clock ordering on any
                # failure, never fails the frame (obs/causal.py)
                await merge_remote(msg.get("hlc"))
                if "watch" in msg:
                    self._deliver_watch(msg["watch"])
                    continue
                fut = self._pending.pop(msg.get("xid"), None)
                if fut and not fut.done():
                    fut.set_result(msg)
        except asyncio.CancelledError:
            pass        # close() cancels us; disconnect handling below
        except ConnectionError:
            pass
        finally:
            if not self._closed:
                self._on_disconnect()

    def _on_disconnect(self) -> None:
        self._connected.clear()
        if self._conn_counted:
            _CONNECTIONS.dec()
            self._conn_counted = False
        self._fail_pending(ConnectionLossError("connection lost"))
        if self._expired or self._closed:
            return
        self._notify("disconnected")
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.create_task(self._reconnect())

    async def _reconnect(self) -> None:
        deadline = time.monotonic() + self._timeout
        # jittered backoff, floored at the reconnect delay and bounded
        # by the session deadline: a coordd outage must not have every
        # client in the shard redialing in lockstep (thundering herd),
        # and the first attempt still lands well inside any
        # disconnect_grace (first delay <= 2 * RECONNECT_DELAY)
        bo = Backoff("coord.reconnect", base=RECONNECT_DELAY, cap=2.0,
                     deadline=deadline)
        while not self._closed and time.monotonic() < deadline:
            await bo.sleep()
            try:
                await self._open_conn(resume=True)
            except (ConnectionLossError, NotLeaderError, OSError):
                continue         # transient / rotated: retry until deadline
            except CoordError:
                break            # server refused the session: expired
            self._refire_watches()
            return
        if not self._closed:
            self._expire()

    def _expire(self) -> None:
        if self._expired:
            return
        self._expired = True
        if self._sess_counted:
            _SESSIONS.dec()
            self._sess_counted = False
        self._watches.clear()
        self._fail_pending(SessionExpiredError(self._session_id or "?"))
        self._notify("expired")

    def _fail_pending(self, err: Exception) -> None:
        for fut in list(self._pending.values()):
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    def _deliver_watch(self, w: dict) -> None:
        key = (w.get("kind"), w.get("path"))
        cbs = self._watches.pop(key, [])
        try:
            event = WatchEvent(EventType(w.get("type")), w.get("path"))
        except ValueError:
            return
        for cb in cbs:
            try:
                cb(event)
            except Exception:
                log.exception("watch callback failed")

    def _refire_watches(self) -> None:
        """After a session resume the server-side watches are gone; fire
        every armed watch so handlers re-read and re-arm."""
        armed = self._watches
        self._watches = {}
        for (kind, path), cbs in armed.items():
            ev = WatchEvent(EventType.DATA_CHANGED
                            if kind == "data" else EventType.CHILDREN_CHANGED,
                            path)
            for cb in cbs:
                try:
                    cb(ev)
                except Exception:
                    log.exception("watch refire failed")

    async def _ping_loop(self) -> None:
        interval = max(self._timeout / 3.0, 0.05)
        try:
            while not self._closed and not self._expired:
                await asyncio.sleep(interval)
                if not self._connected.is_set():
                    continue
                try:
                    await self._request({"op": "ping"})
                except CoordError:
                    pass
        except asyncio.CancelledError:
            pass

    async def _request(self, req: dict) -> dict | list | str | int | None:
        if self._expired:
            raise SessionExpiredError(self._session_id or "?")
        if self._writer is None or self._writer.is_closing():
            raise ConnectionLossError("not connected")
        xid = next(self._xids)
        req["xid"] = xid
        op = str(req.get("op", "?"))
        # trace/span propagation: the server binds both for its own
        # logging and spans, so one trace follows a transition into
        # coordd and the server-side handling nests under our span
        tid = current_trace()
        if tid is not None and "trace" not in req:
            req["trace"] = tid
        sid = current_span_id()
        if sid is not None and "span" not in req:
            req["span"] = sid
        # HLC piggyback (obs/causal.py): every frame carries our clock
        # so the server's handling — and anything it journals — sorts
        # after this send regardless of wall-clock skew
        req["hlc"] = hlc_now()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        t0 = time.monotonic()
        t0_wall = time.time()
        try:
            try:
                if await faults.point("coord.client.send") == "drop":
                    # black-holed frame: we believe it was sent; the
                    # reply never comes.  The caller blocks until the
                    # server heartbeat-expires the silent session and
                    # severs us (or, failing that, until our own reply
                    # deadline below) — exactly an asymmetric partition.
                    pass
                else:
                    self._writer.write(
                        (json.dumps(req) + "\n").encode())
                    await self._writer.drain()
            except (ConnectionError, RuntimeError) as e:
                self._pending.pop(xid, None)
                raise ConnectionLossError(str(e)) from None
            except BaseException:
                # anything else out of the send path (an injected
                # coord.client.send=error, a cancellation) must not
                # strand the xid in _pending for the connection's life
                self._pending.pop(xid, None)
                raise
            try:
                msg = await asyncio.wait_for(
                    fut, _reply_deadline(self._timeout))
            except asyncio.TimeoutError:
                # reply never came while the connection looks healthy:
                # a one-way partition (or a wedged server).  Sever the
                # transport so the read loop unwinds into the normal
                # disconnect/reconnect path — our FIN also lets the
                # server apply its fast disconnect-grace expiry.
                self._pending.pop(xid, None)
                writer = self._writer
                if writer is not None:
                    try:
                        writer.transport.abort()
                    except (AttributeError, RuntimeError):
                        pass
                raise ConnectionLossError(
                    "no reply to %s within %.1fs (one-way partition?)"
                    % (op, _reply_deadline(self._timeout))) from None
        except BaseException as e:
            if op != "ping":
                get_span_store().record(
                    "coord.rpc", ts=t0_wall,
                    dur=time.monotonic() - t0,
                    status=("cancelled"
                            if isinstance(e, asyncio.CancelledError)
                            else "error"),
                    op=op, error=type(e).__name__)
            raise
        dur = time.monotonic() - t0
        _RPC_DUR.observe(dur, op=op)
        # pings are heartbeat noise; everything else is a stage worth
        # attributing in the cross-peer waterfall
        if op != "ping":
            get_span_store().record(
                "coord.rpc", ts=t0_wall, dur=dur,
                status="ok" if msg.get("ok") else "error", op=op)
        if msg.get("ok"):
            return msg.get("result")
        raise _ERRS.get(msg.get("error"), CoordError)(msg.get("msg", ""))

    # ---- ops ----

    def _arm(self, kind: str, path: str, watch: WatchCb | None) -> bool:
        if watch is None:
            return False
        self._watches.setdefault((kind, path), []).append(watch)
        return True

    def _disarm(self, kind: str, path: str, watch: WatchCb) -> None:
        """Error-path cleanup of a just-armed watch.  Tolerant: the entry
        may have been consumed concurrently by _deliver_watch /
        _refire_watches / session expiry, and raising here would mask
        the original CoordError."""
        cbs = self._watches.get((kind, path))
        if cbs and watch in cbs:
            cbs.remove(watch)

    async def create(self, path: str, data: bytes = b"", *,
                     ephemeral: bool = False,
                     sequential: bool = False) -> str:
        return await self._request({
            "op": "create", "path": path,
            "data": base64.b64encode(data).decode(),
            "ephemeral": ephemeral, "sequential": sequential})

    async def get(self, path: str, watch: WatchCb | None = None
                  ) -> tuple[bytes, int]:
        data, version, _ctime = await self.get_full(path, watch)
        return data, version

    async def get_full(self, path: str, watch: WatchCb | None = None
                       ) -> tuple[bytes, int, float]:
        """get() plus the node's creation time — one round trip."""
        armed = self._arm("data", path, watch)
        try:
            res = await self._request({"op": "get", "path": path,
                                       "watch": armed})
        except CoordError:
            if armed:
                self._disarm("data", path, watch)
            raise
        return (base64.b64decode(res["data"]), res["version"],
                res.get("ctime", 0.0))

    async def set(self, path: str, data: bytes, version: int = -1) -> int:
        return await self._request({
            "op": "set", "path": path,
            "data": base64.b64encode(data).decode(), "version": version})

    async def delete(self, path: str, version: int = -1) -> None:
        await self._request({"op": "delete", "path": path,
                             "version": version})

    async def exists(self, path: str, watch: WatchCb | None = None
                     ) -> Stat | None:
        armed = self._arm("data", path, watch)
        try:
            res = await self._request({"op": "exists", "path": path,
                                       "watch": armed})
        except CoordError:
            if armed:
                self._disarm("data", path, watch)
            raise
        if res is None:
            return None
        return Stat(version=res["version"],
                    ephemeral_owner=res.get("ephemeral_owner"),
                    num_children=res.get("num_children", 0),
                    ctime=res.get("ctime", 0.0))

    async def get_children(self, path: str, watch: WatchCb | None = None
                           ) -> list[str]:
        armed = self._arm("children", path, watch)
        try:
            return await self._request({"op": "children", "path": path,
                                        "watch": armed})
        except CoordError:
            if armed:
                self._disarm("children", path, watch)
            raise
    async def multi(self, ops: list[Op]) -> list:
        wire_ops = []
        for op in ops:
            wire_ops.append({
                "kind": op.kind, "path": op.path,
                "data": base64.b64encode(op.data or b"").decode(),
                "version": op.version,
                "ephemeral": op.ephemeral,
                "sequential": op.sequential,
            })
        return await self._request({"op": "multi", "ops": wire_ops})


# ---- session multiplexing (fleet mode) ----
#
# One process running N shards used to open N coordination connections,
# N sessions, and N ping loops against coordd.  CoordMux owns ONE
# NetCoord and hands out refcounted logical handles: every handle is a
# full CoordClient (same reply-deadline, backoff, and reconnect
# semantics — they are the shared NetCoord's), watch delivery is
# demultiplexed back to the arming handle, and session lifecycle
# events fan out to every handle, so each shard's ConsensusMgr reacts
# to an expiry exactly as it would on a private client (it rebuilds
# via its factory, which lands back on the pooled mux — the pool dials
# one fresh connection however many shards rebuild).
#
# The deliberate semantic shift: all handles share one SESSION, so
# every shard's election ephemerals live and die with the process —
# the process is the failure domain, which is exactly what fleet mode
# means (a SIGKILLed fleet sitter fails over all of its shards via one
# FIN + disconnect-grace expiry instead of N session timeouts).


class _HandleWatch:
    """A one-shot watch armed by a handle on the shared client.  When
    the shared read loop fires it, the event is queued to the mux's
    demux pump, which re-attributes it to the arming handle."""

    __slots__ = ("handle", "kind", "path", "cb", "client")

    def __init__(self, handle: "MuxHandle", kind: str, path: str,
                 cb: WatchCb, client: NetCoord):
        self.handle = handle
        self.kind = kind
        self.path = path
        self.cb = cb
        self.client = client      # the generation it was armed on

    def __call__(self, event: WatchEvent) -> None:
        h = self.handle
        h._armed.discard(self)    # consumed (one-shot)
        h._mux._enqueue(h, self.cb, event)


class MuxHandle(CoordClient):
    """One logical coordination client multiplexed over a shared
    connection (see :class:`CoordMux`).  Obtain via
    :meth:`CoordMux.handle` or the process-wide :func:`mux_handle`."""

    def __init__(self, mux: "CoordMux", name: str | None):
        self._mux = mux
        self.name = name
        self._closed = False
        self._session_cbs: list[Callable[[str], None]] = []
        self._armed: set[_HandleWatch] = set()

    def __repr__(self) -> str:
        return "<MuxHandle %s of %r>" % (self.name or "?", self._mux)

    def _client(self) -> NetCoord:
        if self._closed:
            raise ConnectionLossError("mux handle closed")
        c = self._mux._client
        if c is None:
            raise ConnectionLossError("mux not connected")
        return c

    # -- lifecycle --

    async def connect(self) -> None:
        if self._closed:
            raise ConnectionLossError("mux handle closed")
        await self._mux._ensure_client()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        await self._mux._release(self)

    @property
    def session_id(self) -> str | None:
        if self._closed:
            return None
        c = self._mux._client
        return None if c is None else c.session_id

    def on_session_event(self, cb: Callable[[str], None]) -> None:
        self._session_cbs.append(cb)

    def _fire_session(self, event: str) -> None:
        for cb in list(self._session_cbs):
            try:
                cb(event)
            except Exception:
                log.exception("mux session callback failed")

    # -- watch plumbing --

    def _wrap(self, kind: str, path: str, cb: WatchCb | None,
              client: NetCoord) -> _HandleWatch | None:
        if cb is None:
            return None
        w = _HandleWatch(self, kind, path, cb, client)
        self._armed.add(w)
        return w

    # -- ops (delegated; the shared client's semantics apply) --

    async def create(self, path: str, data: bytes = b"", *,
                     ephemeral: bool = False,
                     sequential: bool = False) -> str:
        return await self._client().create(
            path, data, ephemeral=ephemeral, sequential=sequential)

    async def get(self, path: str, watch: WatchCb | None = None
                  ) -> tuple[bytes, int]:
        data, version, _ctime = await self.get_full(path, watch)
        return data, version

    async def get_full(self, path: str, watch: WatchCb | None = None
                       ) -> tuple[bytes, int, float]:
        c = self._client()
        w = self._wrap("data", path, watch, c)
        try:
            return await c.get_full(path, watch=w)
        except CoordError:
            # the shared client disarmed the wrapper from its own
            # table; drop our tracking entry too
            if w is not None:
                self._armed.discard(w)
            raise

    async def set(self, path: str, data: bytes, version: int = -1) -> int:
        return await self._client().set(path, data, version)

    async def delete(self, path: str, version: int = -1) -> None:
        await self._client().delete(path, version)

    async def exists(self, path: str, watch: WatchCb | None = None
                     ) -> Stat | None:
        c = self._client()
        w = self._wrap("data", path, watch, c)
        try:
            return await c.exists(path, watch=w)
        except CoordError:
            if w is not None:
                self._armed.discard(w)
            raise

    async def get_children(self, path: str, watch: WatchCb | None = None
                           ) -> list[str]:
        c = self._client()
        w = self._wrap("children", path, watch, c)
        try:
            return await c.get_children(path, watch=w)
        except CoordError:
            if w is not None:
                self._armed.discard(w)
            raise

    async def multi(self, ops: list[Op]) -> list:
        return await self._client().multi(ops)


class CoordMux:
    """Owns one :class:`NetCoord` (connection + session + ping loop)
    and hands out refcounted :class:`MuxHandle` logical clients.

    Watch demultiplexing runs through a single pump task so delivery
    order is preserved across handles and the ``coord.mux.demux``
    failpoint covers the seam.  When the shared session expires, every
    handle observes ``expired``; the next :meth:`handle` (or
    ``connect``) call rebuilds ONE fresh underlying client for all of
    them.  When the last handle is released the connection is closed
    and the mux (if pooled) leaves the pool."""

    def __init__(self, connstr: str, *, session_timeout: float = 60.0,
                 disconnect_grace: float | None = None,
                 pool_key: tuple | None = None):
        self._connstr = connstr
        self._session_timeout = session_timeout
        self._disconnect_grace = disconnect_grace
        self._pool_key = pool_key
        self._client: NetCoord | None = None
        self._handles: set[MuxHandle] = set()
        self._lock = asyncio.Lock()
        self._queue: asyncio.Queue | None = None
        self._demux_task: asyncio.Task | None = None
        self._closed = False
        # the loop this mux's primitives belong to: a pooled mux is
        # unusable from any OTHER loop (mux_handle evicts it there)
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            self._loop = None

    def __repr__(self) -> str:
        return "<CoordMux %s handles=%d>" % (self._connstr,
                                             len(self._handles))

    @property
    def handle_count(self) -> int:
        return len(self._handles)

    async def handle(self, name: str | None = None) -> MuxHandle:
        """Acquire a connected logical handle (dials the shared
        connection first if needed — raises like NetCoord.connect)."""
        await self._ensure_client()
        if self._closed:
            # lost a race with the last release closing the mux
            raise ConnectionLossError("mux closed")
        h = MuxHandle(self, name)
        self._handles.add(h)
        _MUX_HANDLES.inc()
        return h

    async def _ensure_client(self) -> None:
        async with self._lock:
            if self._closed:
                raise ConnectionLossError("mux closed")
            c = self._client
            if c is not None and not c._expired and not c._closed:
                return
            client = NetCoord(self._connstr,
                              session_timeout=self._session_timeout,
                              disconnect_grace=self._disconnect_grace)
            await client.connect()
            if self._closed:
                # the mux retired (last release / expiry) while we
                # dialed: don't strand a connected client nobody owns
                try:
                    await client.close()
                except (CoordError, OSError):
                    pass
                raise ConnectionLossError("mux closed")
            client.on_session_event(self._on_session)
            self._client = client
            if self._queue is None:
                self._queue = asyncio.Queue()
            if self._demux_task is None or self._demux_task.done():
                self._demux_task = asyncio.create_task(
                    self._demux_loop())

    def _on_session(self, event: str) -> None:
        # fan the shared session's lifecycle out to every logical
        # handle: each shard's ConsensusMgr sees the same 'expired' it
        # would on a private client and rebuilds through its factory
        for h in list(self._handles):
            h._fire_session(event)
        if event == "expired":
            self._retire()

    def _retire(self) -> None:
        """Session expiry is terminal for a NetCoord, so it is terminal
        for the mux built on it: every handle is dead (the layer above
        each one rebuilds through its factory, which lands on a FRESH
        pooled mux — one dial however many shards rebuild).  Retiring
        here is also what keeps refcounts honest: nothing above ever
        close()es an expired client, so dead handles must not hold the
        pool slot open forever."""
        if self._closed:
            return
        self._closed = True
        if self._pool_key is not None \
                and _MUX_POOL.get(self._pool_key) is self:
            del _MUX_POOL[self._pool_key]
        handles = list(self._handles)
        self._handles.clear()
        for h in handles:
            h._closed = True
            h._armed.clear()     # the expired client dropped its table
        if handles:
            _MUX_HANDLES.dec(len(handles))
        # wake the demux pump so it drains and EXITS on its own (we are
        # in a sync callback and cannot await a cancelled task here);
        # the expired client's own tasks self-terminate on its flags
        if self._queue is not None:
            self._queue.put_nowait(None)
        self._client = None

    def _enqueue(self, handle: MuxHandle, cb: WatchCb,
                 event: WatchEvent) -> None:
        q = self._queue
        if q is None or handle._closed:
            return
        q.put_nowait((handle, cb, event))

    async def _demux_loop(self) -> None:
        try:
            while True:
                item = await self._queue.get()
                if item is None:
                    if self._closed:
                        return     # retire sentinel: drain and exit
                    continue
                handle, cb, event = item
                # THE demux seam: one shared connection's watch stream
                # fanning back out to per-shard logical handles.  drop
                # = a lost watch (the anti-entropy pass is the
                # insurance); crash = the sweep's process death here.
                if await faults.point("coord.mux.demux") == "drop":
                    continue
                if handle._closed:
                    continue
                try:
                    cb(event)
                except Exception:
                    log.exception("mux watch callback failed")
        except asyncio.CancelledError:
            raise

    async def _release(self, handle: MuxHandle) -> None:
        if handle not in self._handles:
            return
        self._handles.discard(handle)
        _MUX_HANDLES.dec()
        for w in list(handle._armed):
            # disarm from the client GENERATION each watch was armed
            # on (an expired predecessor already cleared its table;
            # _disarm is tolerant of that)
            w.client._disarm(w.kind, w.path, w)
        handle._armed.clear()
        if not self._handles:
            await self._close_now()

    async def _close_now(self) -> None:
        self._closed = True
        if self._pool_key is not None \
                and _MUX_POOL.get(self._pool_key) is self:
            del _MUX_POOL[self._pool_key]
        # under the mux lock like the spawn site in _ensure_client: the
        # _closed flag above already keeps a racing _ensure_client from
        # respawning the pump, but holding the lock across this
        # load->await->store window makes the discipline checkable
        # (mnt-lint: lockset-inconsistent) instead of an argument in a
        # comment.  No caller holds the lock here: _release comes from
        # handle.close(), the private-mux unwind from mux_handle().
        async with self._lock:
            t = self._demux_task
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
            self._demux_task = None
            client, self._client = self._client, None
        if client is not None:
            try:
                await client.close()
            except (CoordError, OSError):
                pass


# key -> live mux.  Keyed on the full session parameters, not just the
# connstr: two callers asking for different timeouts must not silently
# share a session whose timeout only matches one of them.
_MUX_POOL: dict[tuple, CoordMux] = {}


async def mux_handle(connstr: str, *, session_timeout: float = 60.0,
                     disconnect_grace: float | None = None,
                     name: str | None = None) -> MuxHandle:
    """The process-wide mux pool: every caller asking for the same
    coordd (connstr + session parameters) — fleet-mode shards, a
    single sitter, adm, the test harness — rides ONE TCP connection
    and ONE session.  Returns a connected logical handle; closing the
    last handle closes the connection and empties the pool slot."""
    key = (connstr, float(session_timeout),
           None if disconnect_grace is None else float(disconnect_grace))
    loop = asyncio.get_running_loop()
    while True:
        mux = _MUX_POOL.get(key)
        if mux is not None and mux._loop is not None \
                and mux._loop is not loop:
            if not mux._loop.is_closed():
                # a LIVE loop (another thread) owns the slot: its mux
                # cannot serve this loop, and mutating it cross-thread
                # would tear down shards it is actively running.  This
                # caller rides a private, unpooled mux instead.
                private = CoordMux(connstr,
                                   session_timeout=session_timeout,
                                   disconnect_grace=disconnect_grace)
                try:
                    return await private.handle(name=name)
                except BaseException:
                    await private._close_now()
                    raise
            # a DEAD loop's mux, kept alive by handles its loop died
            # still holding (a leak in that loop's owner): its
            # lock/queue/tasks are bound to the dead loop, so it
            # cannot serve this one.  Drop the slot and settle the
            # gauges the dead loop never will.
            mux._closed = True
            if mux._handles:
                _MUX_HANDLES.dec(len(mux._handles))
                for h in mux._handles:
                    h._closed = True
                mux._handles.clear()
            c, mux._client = mux._client, None
            if c is not None:
                if c._conn_counted:
                    _CONNECTIONS.dec()
                    c._conn_counted = False
                if c._sess_counted:
                    _SESSIONS.dec()
                    c._sess_counted = False
            del _MUX_POOL[key]
            mux = None
        if mux is None or mux._closed:
            mux = CoordMux(connstr, session_timeout=session_timeout,
                           disconnect_grace=disconnect_grace,
                           pool_key=key)
            _MUX_POOL[key] = mux
        try:
            return await mux.handle(name=name)
        except ConnectionLossError:
            if mux._closed:
                continue    # raced the last release; retry on a fresh mux
            if not mux._handles and mux._client is None:
                await mux._close_now()
            raise
        except BaseException:
            # a failed FIRST dial must not leave a dead zero-handle
            # entry squatting the pool slot (its lock is bound to THIS
            # event loop; a later loop reusing the connstr would trip
            # over it).  A mux with live handles stays: the failure
            # belongs to this caller, not to them.
            if not mux._handles and mux._client is None:
                await mux._close_now()
            raise
