"""Client API for the coordination service — the znode data model.

Semantics follow ZooKeeper (what lib/zookeeperMgr.js programs against):

- versioned nodes with compare-and-set writes;
- ephemeral nodes tied to a session, deleted when the session expires;
- sequential nodes with a parent-scoped monotonic 10-digit suffix;
- ONE-SHOT watches on data, existence, and children;
- atomic multi-op transactions;
- sessions that survive TCP disconnects and expire only after the
  session timeout without contact.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from enum import Enum
from typing import Callable


# Delay between a client's reconnect attempts after a dropped
# connection.  Lives here (not in client.py) because the server derives
# its disconnect-grace floor from it — both sides must agree or a
# transient drop could expire a session before its first resume attempt.
RECONNECT_DELAY = 0.2


class CoordError(Exception):
    pass


class NoNodeError(CoordError):
    pass


class NodeExistsError(CoordError):
    pass


class BadVersionError(CoordError):
    pass


class NotEmptyError(CoordError):
    pass


class ConnectionLossError(CoordError):
    pass


class SessionExpiredError(CoordError):
    pass


class NotLeaderError(CoordError):
    """The contacted ensemble member is a follower and refuses client
    sessions; the client should rotate to the hinted leader address."""
    pass


class EventType(str, Enum):
    CREATED = "created"
    DELETED = "deleted"
    DATA_CHANGED = "data_changed"
    CHILDREN_CHANGED = "children_changed"


@dataclass(frozen=True)
class WatchEvent:
    type: EventType
    path: str


WatchCb = Callable[[WatchEvent], None]


@dataclass
class Op:
    """One operation in a multi() transaction."""
    kind: str  # 'create' | 'set' | 'delete' | 'check'
    path: str
    data: bytes | None = None
    version: int = -1
    ephemeral: bool = False
    sequential: bool = False

    @classmethod
    def create(cls, path: str, data: bytes, *, ephemeral: bool = False,
               sequential: bool = False) -> "Op":
        return cls("create", path, data, ephemeral=ephemeral,
                   sequential=sequential)

    @classmethod
    def set(cls, path: str, data: bytes, version: int = -1) -> "Op":
        return cls("set", path, data, version)

    @classmethod
    def delete(cls, path: str, version: int = -1) -> "Op":
        return cls("delete", path, None, version)

    @classmethod
    def check(cls, path: str, version: int = -1) -> "Op":
        return cls("check", path, None, version)


def cluster_state_txn(history_path: str, state_path: str, state: dict,
                      version: int | None) -> list["Op"]:
    """THE state-write transaction (putClusterState contract,
    lib/zookeeperMgr.js:605-630): one persistent-sequential history
    record under *history_path* named by generation, plus the state
    write at *state_path* — a CAS set against *version*, or a fresh
    create when *version* is None (no state yet: state-backfill, first
    bootstrap).

    The single builder shared by the sitter (ConsensusMgr) and the
    operator library (adm): sitter writes and operator writes land in
    the same coordination tree, so the transaction shape must never
    drift between them.  Takes the two paths explicitly — callers keep
    exactly one source of truth for where the shard's tree lives."""
    import json

    data = json.dumps(state).encode()
    ops = [Op.create(
        "%s/%d-" % (history_path, int(state["generation"])),
        data, sequential=True)]
    if version is None:
        ops.append(Op.create(state_path, data))
    else:
        ops.append(Op.set(state_path, data, version))
    return ops


@dataclass
class Stat:
    version: int
    ephemeral_owner: str | None = None
    num_children: int = 0
    ctime: float = 0.0   # unix seconds at creation


class CoordClient(abc.ABC):
    """The narrow interface everything above the coordination layer uses."""

    # -- lifecycle --

    @abc.abstractmethod
    async def connect(self) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None: ...

    @property
    @abc.abstractmethod
    def session_id(self) -> str | None: ...

    @abc.abstractmethod
    def on_session_event(self, cb: Callable[[str], None]) -> None:
        """cb receives 'connected' | 'disconnected' | 'expired'."""

    # -- znode ops --

    @abc.abstractmethod
    async def create(self, path: str, data: bytes = b"", *,
                     ephemeral: bool = False,
                     sequential: bool = False) -> str:
        """Returns the actual path (with sequence suffix if sequential)."""

    @abc.abstractmethod
    async def get(self, path: str, watch: WatchCb | None = None
                  ) -> tuple[bytes, int]: ...

    @abc.abstractmethod
    async def set(self, path: str, data: bytes, version: int = -1) -> int: ...

    @abc.abstractmethod
    async def delete(self, path: str, version: int = -1) -> None: ...

    @abc.abstractmethod
    async def exists(self, path: str, watch: WatchCb | None = None
                     ) -> Stat | None: ...

    @abc.abstractmethod
    async def get_children(self, path: str, watch: WatchCb | None = None
                           ) -> list[str]: ...

    @abc.abstractmethod
    async def multi(self, ops: list[Op]) -> list: ...

    # -- conveniences --

    async def mkdirp(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        cur = ""
        for p in parts:
            cur += "/" + p
            try:
                await self.create(cur)
            except NodeExistsError:
                pass

    async def delete_recursive(self, path: str) -> None:
        try:
            for child in await self.get_children(path):
                await self.delete_recursive(path + "/" + child)
            await self.delete(path)
        except NoNodeError:
            pass


def validate_path(path: str) -> None:
    if not path.startswith("/") or (len(path) > 1 and path.endswith("/")):
        raise CoordError("invalid path: %r" % path)
    if "//" in path:
        raise CoordError("invalid path: %r" % path)
    for comp in path.split("/")[1:]:
        if comp in (".", ".."):
            raise CoordError("invalid path: %r" % path)
