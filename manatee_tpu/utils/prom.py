"""Minimal Prometheus text-exposition builder shared by the peer
status server and coordd (one copy so format fixes land everywhere).

Naming conventions are enforced HERE, not left to each producer:
counters are exported under a ``_total``-suffixed name (a producer that
registers a bare name gets the suffix appended, with the old name kept
as a one-release deprecated alias so existing dashboards keep working),
and duration metrics must be base-unit ``_seconds`` (never ``_ms``).
"""

from __future__ import annotations


def escape_label_value(v: str) -> str:
    """Prometheus text-format escaping for label VALUES: backslash,
    double-quote, and newline must be escaped or a dynamic value (a
    peer name, an error string) silently corrupts the exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def label_str(**kv) -> str:
    """Render '{k="v",...}' with each value escaped.  Use this for any
    label whose value is not a static ASCII literal."""
    if not kv:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, escape_label_value(v)) for k, v in kv.items())


def _escape_help(text: str) -> str:
    # HELP lines escape backslash and newline (not quotes)
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_le(ub: float) -> str:
    """Bucket upper bound as Prometheus renders it ('0.5', '1', '+Inf')."""
    if ub == float("inf"):
        return "+Inf"
    if float(ub).is_integer():
        return str(int(ub))
    return repr(float(ub))


class MetricsBuilder:
    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lines: list[str] = []

    def _family(self, full: str, mtype: str, help_: str) -> None:
        self.lines.append("# HELP %s %s" % (full, _escape_help(help_)))
        self.lines.append("# TYPE %s %s" % (full, mtype))

    def metric(self, name: str, mtype: str, help_: str, samples) -> None:
        """*samples*: a scalar value, or [(label_string, value), ...]
        where label_string is e.g. '{role="leader"}' — build dynamic
        ones with label_str() so the values are escaped.

        A counter whose *name* lacks the conventional ``_total`` suffix
        is exported as ``<name>_total`` AND under the old bare name (a
        deprecated one-release alias), so the convention fix cannot
        silently break an existing scrape."""
        if not isinstance(samples, list):
            samples = [("", samples)]
        if mtype == "counter" and not name.endswith("_total"):
            self._emit(name + "_total", mtype, help_, samples)
            self._emit(name, mtype,
                       "DEPRECATED alias of %s_%s_total; removed next "
                       "release" % (self.prefix, name), samples)
            return
        self._emit(name, mtype, help_, samples)

    def _emit(self, name: str, mtype: str, help_: str,
              samples: list) -> None:
        full = "%s_%s" % (self.prefix, name)
        self._family(full, mtype, help_)
        for labels, value in samples:
            self.lines.append("%s%s %s" % (full, labels, value))

    def histogram(self, name: str, help_: str, buckets, series) -> None:
        """Render one histogram family.  *buckets* is the ascending
        upper-bound list (an implicit +Inf bucket is appended);
        *series* is [(labels_dict, {'counts', 'sum', 'count'}), ...]
        with 'counts' cumulative per explicit bucket."""
        full = "%s_%s" % (self.prefix, name)
        self._family(full, "histogram", help_)
        for labels, s in series:
            for ub, c in zip(buckets, s["counts"]):
                lab = dict(labels)
                lab["le"] = format_le(ub)
                self.lines.append("%s_bucket%s %d"
                                  % (full, label_str(**lab), c))
            lab = dict(labels)
            lab["le"] = "+Inf"
            self.lines.append("%s_bucket%s %d"
                              % (full, label_str(**lab), s["count"]))
            self.lines.append("%s_sum%s %s"
                              % (full, label_str(**labels),
                                 repr(float(s["sum"]))))
            self.lines.append("%s_count%s %d"
                              % (full, label_str(**labels), s["count"]))

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"
