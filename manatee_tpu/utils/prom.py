"""Minimal Prometheus text-exposition builder shared by the peer
status server and coordd (one copy so format fixes land everywhere)."""

from __future__ import annotations


class MetricsBuilder:
    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lines: list[str] = []

    def metric(self, name: str, mtype: str, help_: str, samples) -> None:
        """*samples*: a scalar value, or [(label_string, value), ...]
        where label_string is e.g. '{role="leader"}'."""
        full = "%s_%s" % (self.prefix, name)
        self.lines.append("# HELP %s %s" % (full, help_))
        self.lines.append("# TYPE %s %s" % (full, mtype))
        if not isinstance(samples, list):
            samples = [("", samples)]
        for labels, value in samples:
            self.lines.append("%s%s %s" % (full, labels, value))

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"
