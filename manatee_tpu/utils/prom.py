"""Minimal Prometheus text-exposition builder shared by the peer
status server and coordd (one copy so format fixes land everywhere)."""

from __future__ import annotations


def escape_label_value(v: str) -> str:
    """Prometheus text-format escaping for label VALUES: backslash,
    double-quote, and newline must be escaped or a dynamic value (a
    peer name, an error string) silently corrupts the exposition."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def label_str(**kv) -> str:
    """Render '{k="v",...}' with each value escaped.  Use this for any
    label whose value is not a static ASCII literal."""
    if not kv:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, escape_label_value(v)) for k, v in kv.items())


def _escape_help(text: str) -> str:
    # HELP lines escape backslash and newline (not quotes)
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class MetricsBuilder:
    def __init__(self, prefix: str):
        self.prefix = prefix
        self.lines: list[str] = []

    def metric(self, name: str, mtype: str, help_: str, samples) -> None:
        """*samples*: a scalar value, or [(label_string, value), ...]
        where label_string is e.g. '{role="leader"}' — build dynamic
        ones with label_str() so the values are escaped."""
        full = "%s_%s" % (self.prefix, name)
        self.lines.append("# HELP %s %s" % (full, _escape_help(help_)))
        self.lines.append("# TYPE %s %s" % (full, mtype))
        if not isinstance(samples, list):
            samples = [("", samples)]
        for labels, value in samples:
            self.lines.append("%s%s %s" % (full, labels, value))

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"
