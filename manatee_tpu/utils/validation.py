"""JSON-schema config validation.

Reference parity: lib/postgresMgr.js:60-116 validates the sitter's
postgresMgr config block with a JSON schema at construction time
(lib/postgresMgr.js:257).  We use the `jsonschema` package and raise
ConfigError with a readable message.
"""

from __future__ import annotations

import json
from pathlib import Path

import jsonschema


class ConfigError(Exception):
    pass


def validate_config(cfg: dict, schema: dict, *, name: str = "config") -> dict:
    try:
        jsonschema.validate(cfg, schema)
    except jsonschema.ValidationError as e:
        path = "/".join(str(p) for p in e.absolute_path)
        raise ConfigError("%s invalid at %r: %s" % (name, path, e.message)) from None
    return cfg


def load_json_config(path: str | Path, schema: dict | None = None,
                     *, name: str = "config") -> dict:
    try:
        cfg = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ConfigError("cannot load %s from %s: %s" % (name, path, e)) from None
    if schema is not None:
        validate_config(cfg, schema, name=name)
    return cfg
