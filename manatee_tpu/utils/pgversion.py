"""PostgreSQL version-string handling.

Reference parity: lib/common.js:463-484 (pgStripMinor) — reduce a full
PostgreSQL version to its "major" per the two numbering schemes:

* pre-10 ("9.6.3"): major is the first TWO components → "9.6"
* 10+    ("12.0"):  major is the first component       → "12"

The reference throws on malformed input (asserted in test/tst.common.js and
test/tst.postgresMgr.js:29-43); we raise ValueError.
"""

from __future__ import annotations

import re

_VERSION_RE = re.compile(r"^\d+(\.\d+)*$")


def pg_strip_minor(version: str) -> str:
    if not isinstance(version, str) or not _VERSION_RE.match(version):
        raise ValueError("invalid postgres version: %r" % (version,))
    parts = version.split(".")
    first = int(parts[0])
    if first >= 10:
        return parts[0]
    if len(parts) < 2:
        raise ValueError("pre-10 version must have at least two components: %r"
                         % (version,))
    return ".".join(parts[:2])


def pg_same_major(a: str, b: str) -> bool:
    return pg_strip_minor(a) == pg_strip_minor(b)
