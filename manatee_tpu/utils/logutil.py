"""Structured JSON logging (bunyan-style parity).

The reference logs bunyan JSON records with child loggers per component
(sitter.js:36-42, lib/zookeeperMgr.js:70) and ``-v`` stacking to TRACE
(sitter.js:62-66).  This formatter emits compatible-shaped records:
{"name", "hostname", "pid", "level", "component", "msg", "time"} with
bunyan numeric levels (trace 10 … fatal 60).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import sys
import time

_BUNYAN_LEVELS = {
    logging.DEBUG: 20,
    logging.INFO: 30,
    logging.WARNING: 40,
    logging.ERROR: 50,
    logging.CRITICAL: 60,
}

# logging internals that must never leak into records as "extras":
# every attribute a bare LogRecord carries, plus the ones Formatter and
# asyncio stamp on later.  Anything NOT in this set was passed by the
# caller via extra= (or a filter, e.g. the trace-id filter) and belongs
# in the bunyan record.
_RECORD_INTERNALS = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class BunyanFormatter(logging.Formatter):
    def __init__(self, name: str):
        super().__init__()
        self.name = name
        self.hostname = socket.gethostname()

    def format(self, record: logging.LogRecord) -> str:
        rec = {
            "v": 0,
            "name": self.name,
            "hostname": self.hostname,
            "pid": os.getpid(),
            "level": _BUNYAN_LEVELS.get(record.levelno, 30),
            "component": record.name,
            "msg": record.getMessage(),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S",
                                  time.gmtime(record.created))
                    + ".%03dZ" % (record.msecs,),
        }
        # generic extra-field passthrough: any caller-supplied extra
        # (run_id, rc, duration_ms, trace_id, peer, span, ...) lands in
        # the record without this formatter needing to know its name —
        # but never shadowing the bunyan core fields above
        for attr, value in record.__dict__.items():
            if attr in _RECORD_INTERNALS or attr in rec:
                continue
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            rec[attr] = value
        if record.exc_info:
            rec["err"] = self.formatException(record.exc_info)
        return json.dumps(rec)


def setup_logging(name: str, verbose: int = 0,
                  stream=None) -> None:
    """-v stacking: 0 = INFO, 1 = DEBUG (reference sitter.js:62-66).
    The LOG_LEVEL env var (reference's daemon env knob,
    docs/man/manatee-adm.md in /root/reference:502-515) sets the default
    level, but an explicit -v always wins."""
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(BunyanFormatter(name))
    # stamp the bound trace id on every record (obs/trace.py); the
    # generic extra passthrough above emits it as "trace_id"
    from manatee_tpu.obs.trace import TraceLogFilter
    handler.addFilter(TraceLogFilter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    env_level = os.environ.get("LOG_LEVEL", "").upper()
    if verbose:
        level = logging.DEBUG
    elif env_level in ("TRACE", "DEBUG"):
        level = logging.DEBUG
    elif env_level in ("INFO", "WARN", "WARNING", "ERROR", "FATAL"):
        level = {"INFO": logging.INFO, "WARN": logging.WARNING,
                 "WARNING": logging.WARNING, "ERROR": logging.ERROR,
                 "FATAL": logging.CRITICAL}[env_level]
    else:
        level = logging.INFO
    root.setLevel(level)
