"""Utility layer (reference: lib/common.js, lib/confParser.js)."""

from manatee_tpu.utils.executil import ExecError, ExecResult, run, run_sync
from manatee_tpu.utils.pgversion import pg_strip_minor
from manatee_tpu.utils.confparser import ConfFile

__all__ = [
    "ExecError",
    "ExecResult",
    "run",
    "run_sync",
    "pg_strip_minor",
    "ConfFile",
]
