"""Utility layer (reference: lib/common.js, lib/confParser.js)."""

import datetime as _dt

from manatee_tpu.utils.executil import ExecError, ExecResult, run, run_sync
from manatee_tpu.utils.pgversion import pg_strip_minor
from manatee_tpu.utils.confparser import ConfFile


def iso_ms(when: _dt.datetime | float | None = None) -> str:
    """Millisecond-precision UTC ISO timestamp ('...T...%.3fZ') — the one
    format used for freeze dates, promote expiry, and history times."""
    if when is None:
        dt = _dt.datetime.now(_dt.timezone.utc)
    elif isinstance(when, (int, float)):
        dt = _dt.datetime.fromtimestamp(when, _dt.timezone.utc)
    else:
        dt = when
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


__all__ = [
    "ExecError",
    "ExecResult",
    "run",
    "run_sync",
    "pg_strip_minor",
    "ConfFile",
    "iso_ms",
]
