"""postgresql.conf-style ``key = value`` file read/modify/write.

Reference parity: lib/confParser.js:31-57 (read/set/write via iniparser).
Note the reference's conf generation always starts from the *shipped
template* and rewrites keys programmatically, so unknown keys in the live
file are dropped (lib/postgresMgr.js:2277-2286); callers here follow the
same pattern by loading the template and applying overrides.

Supported syntax: ``key = value``, ``key value`` (postgres accepts both),
``#`` comments, single-quoted values with '' escaping.
"""

from __future__ import annotations

import re
from pathlib import Path

_LINE_RE = re.compile(r"^\s*([A-Za-z_][A-Za-z0-9_.]*)\s*(?:=\s*|\s+)(.*?)\s*$")


def _strip_comment(line: str) -> str:
    """Remove a trailing # comment, honoring single-quoted strings."""
    out = []
    in_quote = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == "'":
            in_quote = not in_quote
        elif c == "#" and not in_quote:
            break
        out.append(c)
        i += 1
    return "".join(out)


def quote_conf_value(value: str) -> str:
    """Single-quote a value for postgresql.conf, escaping embedded quotes.

    Mirrors the synchronous_standby_names quoting the reference needs for
    PG >= 9.6 (lib/postgresMgr.js:184-191)."""
    return "'" + value.replace("'", "''") + "'"


class ConfFile:
    """An ordered key→value view of a postgresql.conf-style file."""

    def __init__(self, entries: dict[str, str] | None = None):
        self._entries: dict[str, str] = dict(entries or {})

    @classmethod
    def from_text(cls, text: str) -> "ConfFile":
        entries: dict[str, str] = {}
        for raw in text.splitlines():
            line = _strip_comment(raw).strip()
            if not line:
                continue
            m = _LINE_RE.match(line)
            if not m:
                continue
            key, val = m.group(1), m.group(2).strip()
            entries[key] = val
        return cls(entries)

    @classmethod
    def read(cls, path: str | Path) -> "ConfFile":
        return cls.from_text(Path(path).read_text())

    def get(self, key: str, default: str | None = None) -> str | None:
        return self._entries.get(key, default)

    def get_unquoted(self, key: str, default: str | None = None) -> str | None:
        v = self._entries.get(key)
        if v is None:
            return default
        if len(v) >= 2 and v[0] == "'" and v[-1] == "'":
            return v[1:-1].replace("''", "'")
        return v

    def set(self, key: str, value: str) -> None:
        self._entries[key] = value

    def delete(self, key: str) -> None:
        self._entries.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def items(self):
        return self._entries.items()

    def to_text(self) -> str:
        return "".join("%s = %s\n" % (k, v) for k, v in self._entries.items())

    def write(self, path: str | Path) -> None:
        """Atomic replace (write temp + rename), the safe analogue of
        lib/common.js:22-60 replacefile semantics."""
        path = Path(path)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(self.to_text())
        tmp.replace(path)
