"""Small asyncio compatibility helpers shared across the tree."""

from __future__ import annotations


def cancel_requests(task) -> int:
    """``task.cancelling()`` (Python >= 3.11), else 0.

    The teardown helpers use the cancel-request count to tell "the task
    I am reaping was cancelled" apart from "I myself am being
    cancelled".  On 3.10 the counter does not exist and the distinction
    cannot be observed; returning 0 degrades to the swallow-and-finish
    behavior instead of crashing with AttributeError mid-teardown.
    """
    if task is None:
        return 0
    cancelling = getattr(task, "cancelling", None)
    return cancelling() if cancelling is not None else 0
