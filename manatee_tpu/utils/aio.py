"""Small asyncio compatibility helpers shared across the tree."""

from __future__ import annotations

import asyncio


async def cancel_and_wait(task, *, poke: float = 1.0) -> None:
    """Cancel *task* and wait until it has actually finished.

    A single ``task.cancel(); await task`` can hang on Python 3.10:
    ``asyncio.wait_for`` swallows a cancellation that lands in the
    same tick its inner future completes (bpo-42130), so a task whose
    body runs queries under wait_for can absorb the one cancel and
    keep looping — and the awaiting ``stop()`` never returns.
    Re-issue the cancel at a short cadence until the task is done.

    A non-cancellation crash inside the task is re-raised, matching
    the plain ``await task`` the callers used before.
    """
    if task is None:
        return
    while not task.done():
        task.cancel()
        await asyncio.wait([task], timeout=poke)
    if not task.cancelled() and task.exception() is not None:
        raise task.exception()


def cancel_requests(task) -> int:
    """``task.cancelling()`` (Python >= 3.11), else 0.

    The teardown helpers use the cancel-request count to tell "the task
    I am reaping was cancelled" apart from "I myself am being
    cancelled".  On 3.10 the counter does not exist and the distinction
    cannot be observed; returning 0 degrades to the swallow-and-finish
    behavior instead of crashing with AttributeError mid-teardown.
    """
    if task is None:
        return 0
    cancelling = getattr(task, "cancelling", None)
    return cancelling() if cancelling is not None else 0
