"""Shared resilience: exponential backoff with full jitter.

Before this module every reconnect/retry loop in the tree slept a fixed
delay (``asyncio.sleep(1.0)`` in the client watcher, ``RETRY_DELAY`` in
the consensus manager and state machine, ``RECONNECT_DELAY`` in the
coord client).  Fixed delays synchronize: after a coordd outage every
client in the shard — and every client of every shard on the box —
retries in lockstep, and the recovering daemon takes the whole herd at
once, repeatedly.  Replicated-transaction systems engineer this out
with exponential backoff plus jitter (SafarDB in PAPERS.md; the classic
AWS full-jitter analysis); this module is that policy, once, with the
observability the rest of the tree expects:

- every backoff sleep increments ``retry_attempts_total{op=...}``;
- every backoff sleep records a ``retry.backoff`` span (op, attempt,
  requested delay), so a partition-era reconnect storm is visible in
  the span feed next to the failover it delayed.

Jitter is "equal jitter" (the AWS backoff analysis's middle scheme):
uniform in ``[d/2, d]`` where ``d`` is the capped exponential delay —
decorrelated across the fleet, never more than 2x the schedule's
retry rate, and no pathological near-zero sleeps busy-spinning a
refused connect.  Deliberate design choice vs the reference's FIXED
delays: early retries are faster (a transient connect blip must not
cost an HA daemon a full 5s of coordination absence), growing to the
old cadence within a few attempts of a sustained outage; the
stateless :func:`backoff_sleep` (watch re-arm, no attempt counter to
grow) instead jitters UP from its fixed delay so that path never
retries faster than the reference.
"""

from __future__ import annotations

import asyncio
import random
import time

from manatee_tpu.obs import get_registry, record_span

_REG = get_registry()
_ATTEMPTS = _REG.counter(
    "retry_attempts_total",
    "backoff sleeps taken by retry/reconnect loops", ("op",))

DEFAULT_BASE = 0.5
DEFAULT_CAP = 10.0
DEFAULT_FACTOR = 2.0


class RetryPolicy:
    """Pure delay schedule: ``min(cap, base * factor**(attempt-1))``
    with optional jitter.  Stateless; share freely."""

    __slots__ = ("base", "cap", "factor", "jitter")

    def __init__(self, *, base: float = DEFAULT_BASE,
                 cap: float = DEFAULT_CAP,
                 factor: float = DEFAULT_FACTOR,
                 jitter: bool = True):
        if base <= 0 or cap < base or factor < 1.0:
            raise ValueError("need 0 < base <= cap and factor >= 1")
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.jitter = bool(jitter)

    def delay_for(self, attempt: int) -> float:
        """Delay before retry *attempt* (1-based)."""
        raw = min(self.cap,
                  self.base * self.factor ** max(0, attempt - 1))
        if not self.jitter:
            return raw
        return random.uniform(raw / 2.0, raw)


class Backoff:
    """One retry loop's state: an attempt counter over a policy.

    ``await bo.sleep()`` before each retry; ``bo.reset()`` on success
    so the next failure starts from the base again.  *deadline*
    (monotonic-clock, optional) caps each sleep so a loop bounded by a
    session timeout never oversleeps its budget.  *sleep_fn* lets the
    state machine keep routing through its swappable ``_sleep`` (the
    model checker replaces it with a zero-delay yield)."""

    __slots__ = ("op", "policy", "deadline", "attempts", "_sleep_fn")

    def __init__(self, op: str, *, policy: RetryPolicy | None = None,
                 deadline: float | None = None, sleep_fn=None,
                 **policy_kw):
        self.op = op
        self.policy = policy or RetryPolicy(**policy_kw)
        self.deadline = deadline
        self.attempts = 0
        self._sleep_fn = sleep_fn or asyncio.sleep

    def reset(self) -> None:
        self.attempts = 0

    async def sleep(self) -> float:
        """Count the attempt, sleep the policy's next delay (clamped to
        the deadline), record metric + span; returns the slept delay."""
        self.attempts += 1
        d = self.policy.delay_for(self.attempts)
        if self.deadline is not None:
            d = max(0.0, min(d, self.deadline - time.monotonic()))
        _ATTEMPTS.inc(op=self.op)
        t0_wall = time.time()
        t0 = time.monotonic()
        await self._sleep_fn(d)
        record_span("retry.backoff", ts=t0_wall,
                    dur=time.monotonic() - t0, op=self.op,
                    attempt=self.attempts, delay=round(d, 3))
        return d


async def backoff_sleep(op: str, delay: float) -> float:
    """One-off jittered sleep for retry paths without loop state (e.g.
    the consensus manager's watch re-arm, whose retry chain is rebuilt
    per firing so no attempt counter survives): sleeps at least
    *delay* plus up to one extra *delay* of decorrelation jitter.
    Jittering DOWN from a fixed delay would be a regression there —
    uniform[0.1d, d] averages ~0.55d, retrying nearly twice as often
    as the fixed schedule against a daemon already struggling.
    Counted and spanned like :meth:`Backoff.sleep`."""
    d = delay + random.uniform(0.0, delay)
    _ATTEMPTS.inc(op=op)
    t0_wall = time.time()
    t0 = time.monotonic()
    await asyncio.sleep(d)
    record_span("retry.backoff", ts=t0_wall,
                dur=time.monotonic() - t0, op=op, attempt=1,
                delay=round(d, 3))
    return d
