"""Child-process exec wrappers with run ids and duration accounting.

Reference parity: lib/common.js:148-172 (zfsExecCommon) runs every zfs
command with an empty environment, a 2 MB output buffer, a per-invocation
run id and duration_ms logging; lib/snapShotter.js:569-611 (_execZfs) layers
the same tracing for snapshot operations.  This module provides the same
contract for any command, both async (asyncio) and sync.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import shlex
import time
from dataclasses import dataclass

from manatee_tpu.utils.aio import cancel_requests

log = logging.getLogger("manatee.exec")

# lib/common.js:151 uses a 2 MB maxBuffer for zfs output.
MAX_OUTPUT_BYTES = 2 * 1024 * 1024

_run_ids = itertools.count(1)
# strong refs to shielded kill/reap cleanups: the loop holds tasks
# weakly, and a GC'd cleanup would leak the very child it reaps
_cleanup_tasks: set = set()


async def kill_and_reap(proc, tasks) -> None:
    """Kill the child and reap it, guaranteed: a cancellation landing
    during the cleanup awaits (e.g. reconfigure cancels the watchdog,
    then close() cancels it again, or a timeout handler's caller is
    cancelled) must not skip the kill/reap — that is exactly the
    orphan these handlers exist to close.  The work runs in a
    shielded, strongly-referenced task so the reap completes even if
    the caller's await is cut (it then finishes detached and a
    CancelledError propagates to the caller — correct in both the
    cancel and the timeout branches)."""
    for t in tasks:
        t.cancel()

    async def _cleanup() -> None:
        await asyncio.gather(*tasks, return_exceptions=True)
        await reap_killed(proc)

    cleanup = asyncio.create_task(_cleanup())
    _cleanup_tasks.add(cleanup)
    cleanup.add_done_callback(_cleanup_tasks.discard)
    await asyncio.shield(cleanup)


@dataclass
class ExecResult:
    argv: list[str]
    returncode: int
    stdout: str
    stderr: str
    duration_ms: float
    run_id: int

    @property
    def ok(self) -> bool:
        return self.returncode == 0


class ExecError(Exception):
    """Command exited non-zero (or was killed by a signal)."""

    def __init__(self, result: ExecResult):
        self.result = result
        super().__init__(
            "command failed (rc=%d): %s: %s"
            % (result.returncode, shlex.join(result.argv), result.stderr.strip())
        )


def _log_result(res: ExecResult) -> None:
    log.debug(
        "exec done",
        extra={
            "run_id": res.run_id,
            "argv": res.argv,
            "rc": res.returncode,
            "duration_ms": round(res.duration_ms, 3),
        },
    )


class OutputLimitExceeded(Exception):
    pass


async def _read_capped(stream: asyncio.StreamReader, cap: int) -> bytes:
    """Read a stream to EOF, erroring once more than *cap* bytes arrive —
    the behavior of the reference's forkexec maxBuffer (lib/common.js:151)."""
    chunks: list[bytes] = []
    total = 0
    while True:
        chunk = await stream.read(65536)
        if not chunk:
            return b"".join(chunks)
        total += len(chunk)
        if total > cap:
            raise OutputLimitExceeded()
        chunks.append(chunk)


async def _pump_stdin(proc: asyncio.subprocess.Process,
                      data: bytes | None) -> None:
    if proc.stdin is None:
        return
    if data:
        proc.stdin.write(data)
        try:
            await proc.stdin.drain()
        except (BrokenPipeError, ConnectionResetError):
            pass
    proc.stdin.close()


async def drain_and_reap(proc: asyncio.subprocess.Process,
                         err_task: "asyncio.Task") -> None:
    """Abort-path cleanup for a child whose stderr is consumed by a
    separate task: the consumer must FINISH (cancellation delivered,
    task done) before reap_killed reads the same StreamReader — a
    concurrent read raises RuntimeError, silently skips the stderr
    drain, and proc.wait() can then block forever on the
    undisconnected pipe.

    A cancellation aimed at the CALLING task while we await here is
    indistinguishable at the except site from err_task's own
    cancellation; finish the cleanup, then re-raise it (tracked via
    Task.cancelling) so callers on except-Exception paths don't
    convert a cancel into a StorageError/swallow it."""
    cur = asyncio.current_task()
    base = cancel_requests(cur)
    err_task.cancel()
    try:
        await err_task
    except asyncio.CancelledError:
        # ours or err_task's own — if it was aimed at us, the
        # cancelling() bookkeeping below re-raises it
        pass
    except Exception:
        pass
    # the reap itself is shielded (like kill_and_reap): a cancel
    # delivered during ITS awaits must not leave the child killed but
    # never waited — the cleanup finishes detached and the cancel is
    # re-raised below
    cleanup = asyncio.create_task(reap_killed(proc))
    _cleanup_tasks.add(cleanup)
    cleanup.add_done_callback(_cleanup_tasks.discard)
    try:
        await asyncio.shield(cleanup)
    except asyncio.CancelledError:
        pass
    if cancel_requests(cur) > base:
        raise asyncio.CancelledError()


async def reap_killed(proc: asyncio.subprocess.Process) -> None:
    """Kill *proc* and wait without deadlocking: asyncio's Process.wait()
    only resolves once every pipe transport disconnects, so abandoned
    stdout/stderr must be drained and stdin closed first."""
    with_suppress = (BrokenPipeError, ConnectionResetError, OSError,
                     RuntimeError)
    try:
        proc.kill()
    except ProcessLookupError:
        pass
    if proc.stdin is not None:
        try:
            proc.stdin.close()
        except with_suppress:
            pass
    for stream in (proc.stdout, proc.stderr):
        if stream is None:
            continue
        try:
            while await stream.read(65536):
                pass
        except with_suppress:
            pass
    await proc.wait()


async def run(
    argv: list[str],
    *,
    empty_env: bool = False,
    env: dict[str, str] | None = None,
    timeout: float | None = None,
    check: bool = True,
    stdin_data: bytes | None = None,
    cwd: str | None = None,
    max_output: int = MAX_OUTPUT_BYTES,
) -> ExecResult:
    """Run *argv* asynchronously; returns ExecResult, raises ExecError if
    ``check`` and the command fails.  ``empty_env`` mirrors the reference's
    habit of exec'ing zfs with ``env: {}`` (lib/common.js:151); output beyond
    ``max_output`` bytes per stream kills the child and errors, like
    forkexec's maxBuffer."""
    run_id = next(_run_ids)
    t0 = time.monotonic()
    proc = await asyncio.create_subprocess_exec(
        *argv,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
        stdin=asyncio.subprocess.PIPE if stdin_data is not None else None,
        env={} if empty_env else env,
        cwd=cwd,
    )
    tasks = [
        asyncio.create_task(_read_capped(proc.stdout, max_output)),
        asyncio.create_task(_read_capped(proc.stderr, max_output)),
        asyncio.create_task(_pump_stdin(proc, stdin_data)),
    ]

    async def _collect():
        # proc.wait() INSIDE the timeout: a child that closes its
        # output pipes but never exits (stuck ioctl, daemonizing
        # wrapper) must still be bounded — waiting outside would hang
        # the caller forever despite the explicit timeout
        out, err, _ = await asyncio.gather(*tasks)
        await proc.wait()
        return out, err

    try:
        out, err = await asyncio.wait_for(_collect(), timeout=timeout)
    except asyncio.CancelledError:
        # the CALLER was cancelled (a watchdog/reconfigure racing this
        # exec): the child must not be orphaned — kill and reap it,
        # then let the cancellation propagate
        await kill_and_reap(proc, tasks)
        raise
    except (asyncio.TimeoutError, OutputLimitExceeded) as e:
        await kill_and_reap(proc, tasks)

        def partial(t) -> bytes:
            # whatever the reader captured before the cut — on the
            # wait()-phase timeout (pipes closed, child never exited)
            # this is the COMPLETE output, the only clue to the wedge
            if t.done() and not t.cancelled() and t.exception() is None:
                return t.result() or b""
            return b""

        why = ("timeout after %ss" % timeout
               if isinstance(e, asyncio.TimeoutError)
               else "output exceeded %d bytes" % max_output)
        err_b = partial(tasks[1])
        res = ExecResult(argv, -9,
                         partial(tasks[0]).decode("utf-8", "replace"),
                         (err_b.decode("utf-8", "replace") + "\n" + why
                          if err_b else why),
                         (time.monotonic() - t0) * 1000.0, run_id)
        _log_result(res)
        raise ExecError(res) from None
    res = ExecResult(
        argv,
        proc.returncode if proc.returncode is not None else -1,
        out.decode("utf-8", "replace"),
        err.decode("utf-8", "replace"),
        (time.monotonic() - t0) * 1000.0,
        run_id,
    )
    _log_result(res)
    if check and res.returncode != 0:
        raise ExecError(res)
    return res


def run_sync(
    argv: list[str],
    *,
    empty_env: bool = False,
    env: dict[str, str] | None = None,
    timeout: float | None = None,
    check: bool = True,
    stdin_data: bytes | None = None,
    cwd: str | None = None,
    max_output: int = MAX_OUTPUT_BYTES,
) -> ExecResult:
    """Synchronous variant of :func:`run` for CLI/tools code paths.
    Shares the async implementation (and its output cap); must not be
    called from inside a running event loop."""
    return asyncio.run(run(
        argv,
        empty_env=empty_env,
        env=env,
        timeout=timeout,
        check=check,
        stdin_data=stdin_data,
        cwd=cwd,
        max_output=max_output,
    ))
