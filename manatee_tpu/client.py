"""Manatee client library — topology watcher for database clients.

Reference parity: the out-of-tree `node-manatee` package
(package.json:51; usage README.md:62-89): clients watch the shard's
cluster state and receive a ``topology`` event with the ORDERED list of
PostgreSQL URLs (primary first, then sync, then asyncs) whenever it
changes, plus a ``ready`` event after the first successful read.
Applications connect to urls[0] for writes and may read from the rest.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable

from manatee_tpu.coord.api import CoordError, NoNodeError
from manatee_tpu.coord.client import NetCoord
from manatee_tpu.utils.retry import Backoff

log = logging.getLogger("manatee.client")


def topology_urls(state: dict) -> list[str]:
    """Ordered pg URLs from a cluster state (primary, sync, asyncs)."""
    urls = [state["primary"]["pgUrl"]]
    if state.get("sync"):
        urls.append(state["sync"]["pgUrl"])
    urls.extend(a["pgUrl"] for a in state.get("async") or [])
    return urls


class ManateeClient:
    """Watches one shard and emits topology changes.

    Events:
      'ready'    (urls)  first successful topology read
      'topology' (urls)  every subsequent change
      'error'    (exc)   unrecoverable coordination failures
    """

    def __init__(self, *, coord_addr: str, shard: str,
                 base_path: str = "/manatee",
                 session_timeout: float = 30.0):
        self._coord_addr = coord_addr   # 'h:p' or ensemble 'h1:p1,h2:p2'
        self._path = "%s/%s/state" % (base_path.rstrip("/"), shard)
        self._session_timeout = session_timeout
        self._client: NetCoord | None = None
        self._listeners: dict[str, list[Callable]] = {}
        self._topology: list[str] | None = None
        self._ready = False
        self._closed = False
        self._task: asyncio.Task | None = None

    # -- events --

    def on(self, event: str, cb: Callable) -> None:
        self._listeners.setdefault(event, []).append(cb)

    def _emit(self, event: str, payload) -> None:
        for cb in self._listeners.get(event, []):
            try:
                cb(payload)
            except Exception:
                log.exception("client listener for %s failed", event)

    @property
    def topology(self) -> list[str] | None:
        return self._topology

    # -- lifecycle --

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass       # the cancel we just requested
            except Exception:
                # a watcher that already died of an unexpected error:
                # its stale exception must not abort the teardown
                log.exception("client watcher died uncleanly")
        if self._client:
            await self._client.close()

    async def _run(self) -> None:
        # jittered exponential backoff between (re)connect attempts: a
        # coordd outage ends with every database client in the fleet
        # re-dialing, and the old fixed 1s sleep made them hammer the
        # recovering daemon in lockstep — the thundering herd the
        # shared retry policy exists to break
        bo = Backoff("client.reconnect", base=0.5, cap=10.0)
        while not self._closed:
            client = None
            try:
                client = NetCoord(self._coord_addr,
                                  session_timeout=self._session_timeout)
                await client.connect()
                self._client = client
                expired = asyncio.Event()
                client.on_session_event(
                    lambda ev: expired.set() if ev == "expired" else None)
                # the backoff resets only once the session demonstrably
                # SERVES (a first successful read inside the watch
                # loop) — resetting on mere connect would let a coordd
                # that accepts sessions and then dies keep the whole
                # fleet re-dialing at base cadence
                await self._watch_loop(client, expired, bo)
            except asyncio.CancelledError:
                return
            except (CoordError, OSError) as e:
                log.warning("client coordination error: %s; retrying "
                            "(attempt %d)", e, bo.attempts + 1)
                self._emit("error", e)
            finally:
                if client is not None:
                    try:
                        await client.close()
                    except (CoordError, OSError):
                        pass
            await bo.sleep()

    async def _watch_loop(self, client: NetCoord,
                          expired: asyncio.Event,
                          bo: Backoff | None = None) -> None:
        while not self._closed and not expired.is_set():
            changed = asyncio.Event()
            try:
                data, _v = await client.get(self._path,
                                            watch=lambda e: changed.set())
            except NoNodeError:
                stat = await client.exists(self._path,
                                           watch=lambda e: changed.set())
                if bo is not None:
                    bo.reset()   # the session answered; it serves
                if stat is None:
                    await self._wait_either(changed, expired)
                    continue
                data, _v = await client.get(self._path)
            # first successful read: the session serves, so the next
            # failure's backoff schedule starts from the base again
            if bo is not None:
                bo.reset()
            try:
                state = json.loads(data.decode())
                urls = topology_urls(state)
            except (ValueError, KeyError, TypeError):
                # malformed or partial state (e.g. "primary": null from
                # hand-edited tooling): skip, keep watching
                await self._wait_either(changed, expired)
                continue
            if urls != self._topology:
                self._topology = urls
                if not self._ready:
                    self._ready = True
                    self._emit("ready", urls)
                else:
                    self._emit("topology", urls)
            await self._wait_either(changed, expired)

    @staticmethod
    async def _wait_either(a: asyncio.Event, b: asyncio.Event) -> None:
        ta = asyncio.create_task(a.wait())
        tb = asyncio.create_task(b.wait())
        try:
            await asyncio.wait([ta, tb],
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            ta.cancel()
            tb.cancel()
