"""ctypes loader for the native stream pump (native/streampump.cpp).

The pump splices pipe->socket bytes in the kernel — the bulk-transfer
primitive SURVEY.md §7 names as the one native-code candidate (the
reference's equivalent is `zfs send | socket` piped by the kernel,
lib/backupSender.js:172-180).  It is wired into the SENDER side of the
backup plane behind MANATEE_NATIVE=1: DirBackend._send_native and
ZfsBackend._send_native splice tar's / `zfs send`'s stdout straight to
the peer socket in a worker thread, freeing the event loop of the
byte-shoveling.  See native/BENCH.md for the measured two-process
transfer numbers (the kernel path wins once the receiver is not the
bottleneck, and never loses).
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Callable

_LIB_NAME = "libstreampump.so"
_lib: ctypes.CDLL | None = None
_load_tried = False

_PROGRESS_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_longlong)


def _find_lib() -> str | None:
    env = os.environ.get("MANATEE_NATIVE_LIB")
    if env:
        return env if os.path.exists(env) else None
    cand = Path(__file__).resolve().parent.parent / "native" / _LIB_NAME
    return str(cand) if cand.exists() else None


def available() -> bool:
    return _load() is not None


def enabled() -> bool:
    """available AND explicitly opted in via MANATEE_NATIVE=1."""
    return bool(os.environ.get("MANATEE_NATIVE")) and available()


def _load() -> ctypes.CDLL | None:
    global _lib, _load_tried
    if _load_tried:
        return _lib
    _load_tried = True
    if os.environ.get("MANATEE_NO_NATIVE"):
        return None
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.mnt_pump.restype = ctypes.c_longlong
        lib.mnt_pump.argtypes = [ctypes.c_int, ctypes.c_int,
                                 _PROGRESS_CB]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def pump(fd_in: int, fd_out: int,
         progress: Callable[[int], bool] | None = None) -> int:
    """Blocking pump fd_in -> fd_out until EOF.  Run it in a thread.
    *progress(total)* returning True aborts.  Returns bytes pumped;
    raises OSError on pump failure."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native pump not available")

    if progress is not None:
        def cb(total: int) -> int:
            try:
                return 1 if progress(total) else 0
            except Exception:
                return 1
        c_cb = _PROGRESS_CB(cb)
    else:
        c_cb = _PROGRESS_CB(0)

    res = lib.mnt_pump(fd_in, fd_out, c_cb)
    if res < 0:
        raise OSError(-res, os.strerror(-res))
    return int(res)
