"""The failpoint catalog: every named fault-injection seam in the tree.

One entry per point: a human description plus the source files allowed
to invoke it.  The catalog is the single source of truth three consumers
share:

- :func:`manatee_tpu.faults.point` refuses to ARM a name that is not
  here (typo protection: a fault armed against a misspelled point would
  silently never fire);
- the ``faultpoint-unregistered`` mnt-lint rule verifies every
  ``faults.point("...")`` call site names a cataloged point AND lives in
  the file the catalog binds it to (which is what makes point names
  globally unique — two seams cannot share a name);
- ``docs/fault-injection.md`` documents exactly this set, and
  tests/test_faults.py asserts the doc and the catalog cannot drift.

Keep entries sorted by name.  ``drop`` support is a per-seam property
(a black hole only means something where bytes travel); the lists here
say which actions each site honors.  ``crash`` is supported at EVERY
seam — process death is meaningful anywhere — and the crash-recovery
sweep (tests/test_crash_sweep.py, docs/crash-recovery.md) enforces via
a sync test that every entry here has a sweep scenario crashing a live
shard exactly at that seam and proving recovery.
"""

from __future__ import annotations

# name -> (description, (allowed source files...), (supported actions...))
# Paths are repo-relative and matched by suffix, so the rule works no
# matter how the linter was invoked.
CATALOG: dict[str, tuple[str, tuple[str, ...], tuple[str, ...]]] = {
    "backup.negotiate_base": (
        "backup server's common-base intersection for an incremental "
        "rebuild (POST /backup `bases` offer); error degrades the job "
        "to a full stream",
        ("manatee_tpu/backup/server.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "backup.post": (
        "restore client's POST /backup to the upstream's backup server; "
        "drop = the request is black-holed (reads as a timeout)",
        ("manatee_tpu/backup/client.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "backup.recv.stream": (
        "restore client's inbound snapshot stream, at accept time; "
        "drop = the accepted connection is severed before any byte "
        "is consumed",
        ("manatee_tpu/backup/client.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "backup.send.connect": (
        "backup sender's dial-back to the requester's receive "
        "listener; drop = the SYN is black-holed (reads as a connect "
        "timeout)",
        ("manatee_tpu/backup/sender.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "backup.send.stream": (
        "backup sender's snapshot stream, before the first byte; "
        "stall models a wedged send",
        ("manatee_tpu/backup/sender.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "coord.client.connect": (
        "sitter-side dial+handshake to coordd; drop = the SYN is "
        "black-holed (connection loss), the partition primitive",
        ("manatee_tpu/coord/client.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "coord.client.recv": (
        "inbound coordd frame delivery (replies and watch pushes); "
        "drop = the frame vanishes in flight — a ONE-way partition "
        "(outbound heartbeats keep the session alive) the client "
        "detects via its reply deadline and severs",
        ("manatee_tpu/coord/client.py",),
        ("delay", "drop", "crash"),
    ),
    "coord.client.send": (
        "outbound coordd RPC frame write (pings included); drop = the "
        "frame is black-holed — the session dies of heartbeat silence "
        "while the process lives, the partition primitive",
        ("manatee_tpu/coord/client.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "coord.hlc.merge": (
        "inbound hybrid-logical-clock stamp merge (every piggyback "
        "boundary: coord frames, written state, POST /backup, prober "
        "clock probes); error degrades that record to wall-clock "
        "ordering — it must never fail the carrying RPC",
        ("manatee_tpu/obs/causal.py",),
        ("error", "delay", "crash"),
    ),
    "coord.mux.demux": (
        "mux watch demultiplexer: where one shared coordd "
        "connection's watch stream fans back out to per-shard logical "
        "handles (fleet mode); drop = a lost watch the anti-entropy "
        "pass must heal, stall = the whole mux's watch plane wedges "
        "until cleared",
        ("manatee_tpu/coord/client.py",),
        ("delay", "stall", "drop", "crash"),
    ),
    "coord.put_state": (
        "consensus manager's durable cluster-state transaction "
        "(state + history, one multi)",
        ("manatee_tpu/coord/manager.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "coordd.dispatch": (
        "coordd server-side request dispatch; drop = the request is "
        "consumed but never answered",
        ("manatee_tpu/coord/server.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "coordd.oplog.append": (
        "coordd durable op-log append (error injects a disk-write "
        "failure, exercising the synchronous-snapshot fallback)",
        ("manatee_tpu/coord/server.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "obs.history.append": (
        "metric-history segment append (snapshot serialize + fsync); "
        "a crash here can tear at most the final line, which the "
        "doctor notes but never counts as damage",
        ("manatee_tpu/obs/history.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "obs.incident.collect": (
        "incident evidence collector, before the fleet fan-out; a "
        "crash mid-collection must leave no partial report artifact "
        "(reports land via tmp+rename)",
        ("manatee_tpu/obs/incident.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "obs.loop.tick": (
        "loop monitor's self-timing tick (each pass); stall wedges "
        "the tick coroutine WITHOUT blocking the loop — the watchdog "
        "must not report a stall for it",
        ("manatee_tpu/obs/profile.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "obs.profile.sample": (
        "profiler's aggregation pass (pending folded stacks -> the "
        "bounded ring), on the event loop; error/stall starve "
        "GET /profile of fresh buckets but never the daemon",
        ("manatee_tpu/obs/profile.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "pg.catchup": (
        "primary's wait-for-standby-catchup poll loop (each pass); "
        "stall keeps the primary read-only — a stalled takeover",
        ("manatee_tpu/pg/manager.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "pg.promote": (
        "pg manager's primary transition, before promotion",
        ("manatee_tpu/pg/manager.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "pg.repoint": (
        "standby's live upstream re-point (reload fast path)",
        ("manatee_tpu/pg/manager.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "pg.restore": (
        "standby's full restore from the upstream's backup server, "
        "before the transfer starts",
        ("manatee_tpu/pg/manager.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "prober.read": (
        "prober's staleness-bounded read probe against one replica, "
        "before the query is issued; error counts a bad read-SLI "
        "event without touching the cluster",
        ("manatee_tpu/daemons/prober.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "prober.write": (
        "prober's synthetic write probe against the shard's primary, "
        "before the insert; error counts a bad write-SLI event and "
        "opens a measured error window",
        ("manatee_tpu/daemons/prober.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "reshard.cleanup": (
        "resharder's cleanup step, before the topology unfreeze and "
        "the done-record CAS; a crash here leaves a flipped, serving "
        "split whose source topology is still frozen (resume "
        "finishes the bookkeeping)",
        ("manatee_tpu/reshard/orchestrator.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "reshard.delta": (
        "resharder's incremental catch-up round (and the post-freeze "
        "final delta), before the restore is issued; drop = the "
        "round is skipped and the step fails",
        ("manatee_tpu/reshard/orchestrator.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "reshard.flip": (
        "resharder's cutover CAS seam: the boot hold is released and "
        "the target is writable, but the shard map has NOT yet "
        "changed hands — a crash here must leave the source the "
        "sole owner until resume re-runs the flip",
        ("manatee_tpu/reshard/orchestrator.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "reshard.freeze": (
        "resharder's freeze step, before the source range goes "
        "frozen in the shard map; a crash here leaves everything "
        "serving (abort and resume both trivially reconverge)",
        ("manatee_tpu/reshard/orchestrator.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "reshard.seed": (
        "resharder's initial full seed of the target dataset, before "
        "the restore is issued; drop = the seed is skipped and the "
        "step fails",
        ("manatee_tpu/reshard/orchestrator.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "router.accept": (
        "router's client-connection accept, before the first request "
        "line is read; drop = the connection is closed without a "
        "byte (clients retry-connect)",
        ("manatee_tpu/daemons/router.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "router.park": (
        "router's park entry: a write found no writable primary and "
        "is about to be held for replay; stall models a park that "
        "never wakes (bounded by the client's own timeout)",
        ("manatee_tpu/daemons/router.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "router.relay": (
        "router's per-request relay, after the verb sniff and before "
        "the routing decision; drop = the request is consumed and "
        "never answered (a black-holed proxy hop)",
        ("manatee_tpu/daemons/router.py",),
        ("error", "delay", "stall", "drop", "crash"),
    ),
    "state.write": (
        "state machine's durable CAS write of a decided transition",
        ("manatee_tpu/state/machine.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.delta.apply": (
        "delta apply on the restore receiver, after the target "
        "dataset materialized but before the base clone + extraction "
        "(both backends' apply seam; dirstore call site) — a crash "
        "here leaves the half-applied debris the sweep destroys, and "
        "the retry goes full",
        ("manatee_tpu/storage/dirstore.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.delta.send": (
        "incremental snapshot send (manifest diff + changed-file "
        "stream), before anything is written to the wire (both "
        "backends; dirstore and zfs call sites)",
        ("manatee_tpu/storage/dirstore.py",
         "manatee_tpu/storage/zfsbackend.py"),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.recv": (
        "dir-backend stream receive into a dataset (restore data "
        "path)",
        ("manatee_tpu/storage/dirstore.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.send": (
        "dir-backend snapshot stream send (backup data path)",
        ("manatee_tpu/storage/dirstore.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.snapshot": (
        "dir-backend snapshot creation (the transition snapshot and "
        "the snapshotter ride this)",
        ("manatee_tpu/storage/dirstore.py",),
        ("error", "delay", "stall", "crash"),
    ),
    "storage.zfs.exec": (
        "every zfs(8) command the ZFS backend runs (one seam for the "
        "whole command family)",
        ("manatee_tpu/storage/zfsbackend.py",),
        ("error", "delay", "stall", "crash"),
    ),
}


def describe(name: str) -> str:
    return CATALOG[name][0]


def files_for(name: str) -> tuple[str, ...]:
    return CATALOG[name][1]


def actions_for(name: str) -> tuple[str, ...]:
    return CATALOG[name][2]
