"""Live fault injection: a process-wide registry of named failpoints.

The model checker (`state/modelcheck.py`) explores partitions and
stalls in a simulated world; this package is the LIVE-stack
counterpart: every real I/O seam — coord RPC framing, the backup
stream, pg manager transitions, storage commands, the durable
cluster-state write — calls :func:`point` with a name from
:mod:`manatee_tpu.faults.catalog`, and operators/tests arm faults
against those names to reproduce the ugly failure modes SIGKILL cannot:
alive-but-unreachable peers, slow links, stalled transfers, failed disk
writes.

Actions (per armed rule):

- ``error``   raise a typed exception (``error:<TypeName>``; default
  :class:`FaultError`) — the call site's own handling then runs;
- ``delay``   sleep ``delay`` seconds (plus up to ``jitter`` more) and
  continue — a slow link/disk;
- ``stall``   block until the rule is cleared — a wedge an operator
  heals with ``manatee-adm fault clear``;
- ``drop``    black-hole: :func:`point` returns ``"drop"`` and the call
  site applies its documented no-bytes-travel behavior (skip the
  write, discard the frame, refuse the connect).  Arming ``drop`` on
  the ``coord.client.*`` points of one peer is a live asymmetric
  network partition: the process stays up, its pg keeps running, but
  its coordination traffic vanishes — the real-stack analogue of the
  model checker's ``partition`` scenario.
- ``crash``   the process terminates ITSELF at the seam, un-catchably
  — ``crash`` / ``crash:exit`` is a hard ``os._exit(CRASH_EXIT_CODE)``
  (no atexit, no finally, no daemon signal handlers), ``crash:kill``
  is SIGKILL-to-self (the kernel path, indistinguishable from an OOM
  kill).  This is what makes the crash-recovery sweep deterministic:
  instead of killing a peer at a scheduler-chosen instant, the sweep
  arms ``<point>=crash`` and the process dies exactly AT the
  dangerous seam — mid-promote, mid-oplog-append, mid-restore
  (docs/crash-recovery.md).

Triggers compose onto any action: ``count=N`` injects at most N times
(``count=1`` = one-shot), ``prob=P`` injects each pass with probability
P.  An exhausted rule stays listed (hits visible) until cleared.

Arming surfaces:

- boot: the ``MANATEE_FAULTS`` environment variable (``;``-separated
  specs) or a ``faults`` list in the sitter/backupserver config;
- runtime: ``POST /faults`` on the status server, the backup REST
  server, and coordd's metrics listener (``GET`` lists, ``DELETE``
  clears) — each arms the registry of ITS OWN process;
- operator: ``manatee-adm fault set|list|clear`` fans out over the
  shard's peers.

Spec syntax (shared by all of the above)::

    <point>=<action>[:<arg>][,<key>=<val>...]

    coord.client.send=drop
    pg.restore=error:StorageError,count=1
    coord.client.recv=delay:0.5,jitter=0.3,prob=0.2
    backup.send.stream=stall

The fast path — no fault armed anywhere — is a None check; a shard
that never arms anything pays nothing measurable.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import signal
import sys
import time

from manatee_tpu.faults.catalog import CATALOG, actions_for
from manatee_tpu.obs import get_journal, get_registry

log = logging.getLogger("manatee.faults")

_REG = get_registry()
_INJECTIONS = _REG.counter(
    "fault_injections_total",
    "faults injected at live failpoints", ("point", "action"))

ACTIONS = ("error", "delay", "drop", "stall", "crash")

# the os._exit status a `crash`/`crash:exit` rule dies with — distinctive
# so a sweep can tell "crashed at the armed seam" (this code) from "died
# of something else" (anything else); crash:kill dies of SIGKILL instead
# (waitpid status -9), the kernel path no userland fingerprint survives
CRASH_EXIT_CODE = 86
CRASH_VARIANTS = ("exit", "kill")


class FaultError(Exception):
    """The default injected error (also the arming-API error type)."""


class FaultSpecError(FaultError):
    """A malformed or uncataloged fault spec."""


# error: names resolvable without import cycles; module-path entries
# resolve lazily at raise time
_BUILTIN_ERRORS = {
    "FaultError": lambda: FaultError,
    "OSError": lambda: OSError,
    "ConnectionError": lambda: ConnectionError,
    "ConnectionResetError": lambda: ConnectionResetError,
    "TimeoutError": lambda: asyncio.TimeoutError,
}
_LAZY_ERRORS = {
    "CoordError": ("manatee_tpu.coord.api", "CoordError"),
    "ConnectionLossError": ("manatee_tpu.coord.api", "ConnectionLossError"),
    "PgError": ("manatee_tpu.pg.engine", "PgError"),
    "StorageError": ("manatee_tpu.storage.base", "StorageError"),
}


def resolve_error(name: str):
    """The exception class an ``error:<name>`` spec raises."""
    if name in _BUILTIN_ERRORS:
        return _BUILTIN_ERRORS[name]()
    entry = _LAZY_ERRORS.get(name)
    if entry is None:
        raise FaultSpecError(
            "unknown error type %r (known: %s)"
            % (name, ", ".join(sorted(list(_BUILTIN_ERRORS)
                                      + list(_LAZY_ERRORS)))))
    import importlib
    mod = importlib.import_module(entry[0])
    return getattr(mod, entry[1])


class FaultRule:
    """One armed fault: an action plus its triggers, bound to a point."""

    __slots__ = ("rule_id", "pt", "action", "error", "delay", "jitter",
                 "count", "prob", "hits", "armed_at", "source",
                 "variant", "_cleared")

    def __init__(self, rule_id: int, pt: str, action: str, *,
                 error: str = "FaultError", delay: float = 0.0,
                 jitter: float = 0.0, count: int | None = None,
                 prob: float | None = None, variant: str = "exit",
                 source: str = "api"):
        self.rule_id = rule_id
        self.pt = pt
        self.action = action
        self.error = error
        self.variant = variant
        self.delay = float(delay)
        self.jitter = float(jitter)
        self.count = None if count is None else int(count)
        self.prob = None if prob is None else float(prob)
        self.hits = 0
        self.armed_at = time.time()
        self.source = source
        # stall rules block on this; clear() releases them.  Event() is
        # loop-agnostic at construction (py>=3.10), so env-time arming
        # (no loop yet) is safe.
        self._cleared = asyncio.Event()

    @property
    def exhausted(self) -> bool:
        return self.count is not None and self.hits >= self.count

    def should_fire(self) -> bool:
        if self.exhausted:
            return False
        if self.prob is not None and random.random() >= self.prob:
            return False
        return True

    def to_dict(self) -> dict:
        return {
            "id": self.rule_id,
            "point": self.pt,
            "action": self.action,
            "error": self.error if self.action == "error" else None,
            "variant": self.variant if self.action == "crash" else None,
            "delay": self.delay if self.action == "delay" else None,
            "jitter": self.jitter if self.action == "delay" else None,
            "count": self.count,
            "prob": self.prob,
            "hits": self.hits,
            "exhausted": self.exhausted,
            "armed_at": round(self.armed_at, 3),
            "source": self.source,
        }


def parse_spec(spec: str) -> dict:
    """``point=action[:arg][,k=v...]`` -> arm() kwargs.  Raises
    :class:`FaultSpecError` with a usable message on any malformation
    (this surfaces verbatim in the CLI and the HTTP 400)."""
    spec = spec.strip()
    pt, sep, rest = spec.partition("=")
    if not sep or not pt or not rest:
        raise FaultSpecError(
            "bad fault spec %r (want point=action[:arg][,k=v...])"
            % spec)
    head, *opts = rest.split(",")
    action, _, arg = head.partition(":")
    action = action.strip()
    if action not in ACTIONS:
        raise FaultSpecError("unknown action %r (one of %s)"
                             % (action, "/".join(ACTIONS)))
    kw: dict = {"point": pt.strip(), "action": action}
    if arg:
        if action == "error":
            kw["error"] = arg.strip()
        elif action == "delay":
            try:
                kw["delay"] = float(arg)
            except ValueError:
                raise FaultSpecError("bad delay %r" % arg) from None
        elif action == "crash":
            kw["variant"] = arg.strip()
        else:
            raise FaultSpecError("action %r takes no argument" % action)
    for opt in opts:
        k, s, v = opt.partition("=")
        k = k.strip()
        if not s or k not in ("count", "prob", "delay", "jitter",
                              "error", "variant"):
            raise FaultSpecError("bad fault option %r" % opt)
        try:
            if k == "count":
                kw[k] = int(v)
            elif k in ("prob", "delay", "jitter"):
                kw[k] = float(v)
            else:
                kw[k] = v.strip()
        except ValueError:
            raise FaultSpecError("bad value for %s: %r" % (k, v)) \
                from None
    return kw


def validate_arm(*, point: str, action: str,
                 error: str = "FaultError", delay: float = 0.0,
                 jitter: float = 0.0, count: int | None = None,
                 prob: float | None = None,
                 variant: str = "exit") -> None:
    """Every arm-time check, side-effect free — so batch arming can
    validate ALL specs before arming ANY (a multi-spec `fault set`
    with a typo must not leave the target half-armed), and the CLI can
    fail fast client-side with the same rules.  Options irrelevant to
    the action are rejected too: a misdirected option means the
    operator expects behavior the rule will never deliver."""
    if point not in CATALOG:
        raise FaultSpecError(
            "unknown failpoint %r (see docs/fault-injection.md; "
            "GET /faults lists the catalog)" % point)
    if action not in ACTIONS:
        raise FaultSpecError("unknown action %r" % action)
    if action not in actions_for(point):
        raise FaultSpecError(
            "point %r does not support %r (supported: %s)"
            % (point, action, "/".join(actions_for(point))))
    if action == "error":
        resolve_error(error)            # typo protection at arm time
    elif error != "FaultError":
        raise FaultSpecError(
            "error=%s only applies to the error action" % error)
    if action == "crash":
        if variant not in CRASH_VARIANTS:
            raise FaultSpecError(
                "unknown crash variant %r (one of %s)"
                % (variant, "/".join(CRASH_VARIANTS)))
        if prob is not None or count is not None:
            # the process dies on the first hit — a count/prob trigger
            # promises later injections that can never happen
            raise FaultSpecError(
                "count/prob do not apply to the crash action (the "
                "first hit terminates the process)")
    elif variant != "exit":
        raise FaultSpecError(
            "variant=%s only applies to the crash action" % variant)
    if action == "delay":
        if delay <= 0:
            raise FaultSpecError("delay must be > 0 (got %r)" % delay)
        if jitter < 0:
            raise FaultSpecError("jitter must be >= 0 (got %r)"
                                 % jitter)
    elif delay or jitter:
        raise FaultSpecError(
            "delay/jitter only apply to the delay action")
    if count is not None and count < 1:
        raise FaultSpecError("count must be >= 1")
    if prob is not None and not (0.0 < prob <= 1.0):
        raise FaultSpecError("prob must be in (0, 1]")


def validate_spec(spec: str) -> dict:
    """Parse AND fully validate one spec string (catalog membership,
    supported action, trigger ranges); returns the arm() kwargs."""
    kw = parse_spec(spec)
    validate_arm(**kw)
    return kw


class FaultRegistry:
    """Per-process armed-fault state.  One instance per daemon (see
    :func:`get_faults`); everything is event-loop-thread confined, like
    the obs registries."""

    def __init__(self):
        self._rules: dict[str, list[FaultRule]] = {}
        self._next_id = 1

    # -- arming --

    def arm(self, *, point: str, action: str, error: str = "FaultError",
            delay: float = 0.0, jitter: float = 0.0,
            count: int | None = None, prob: float | None = None,
            variant: str = "exit", source: str = "api") -> FaultRule:
        validate_arm(point=point, action=action, error=error,
                     delay=delay, jitter=jitter, count=count,
                     prob=prob, variant=variant)
        rule = FaultRule(self._next_id, point, action, error=error,
                         delay=delay, jitter=jitter, count=count,
                         prob=prob, variant=variant, source=source)
        self._next_id += 1
        self._rules.setdefault(point, []).append(rule)
        log.warning("fault armed: %s -> %s (count=%s prob=%s) [%s]",
                    point, action, count, prob, source)
        get_journal().record("fault.armed", point=point, action=action,
                             count=count, prob=prob, source=source)
        return rule

    def arm_spec(self, spec: str, *, source: str = "api") -> FaultRule:
        return self.arm(source=source, **parse_spec(spec))

    # -- clearing --

    def clear(self, point: str | None = None,
              rule_id: int | None = None) -> int:
        """Disarm rules (all, one point's, or one id); stalled callers
        are released and proceed.  Returns the number removed."""
        removed: list[FaultRule] = []
        for pt in list(self._rules):
            if point is not None and pt != point:
                continue
            keep = []
            for r in self._rules[pt]:
                if rule_id is not None and r.rule_id != rule_id:
                    keep.append(r)
                else:
                    removed.append(r)
            if keep:
                self._rules[pt] = keep
            else:
                del self._rules[pt]
        for r in removed:
            r._cleared.set()
        if removed:
            get_journal().record(
                "fault.cleared", point=point or "*",
                rules=[r.rule_id for r in removed])
            log.warning("fault cleared: %s (%d rule(s))",
                        point or "*", len(removed))
        return len(removed)

    def list(self) -> list[dict]:
        out = []
        for pt in sorted(self._rules):
            out.extend(r.to_dict() for r in self._rules[pt])
        return out

    def __len__(self) -> int:
        return sum(len(v) for v in self._rules.values())

    # -- firing --

    async def fire(self, name: str) -> str:
        rules = self._rules.get(name)
        if not rules:
            return "ok"
        verdict = "ok"
        for rule in list(rules):
            # re-check liveness each pass: an earlier rule's await (a
            # stall the operator just released, a delay) may have seen
            # the WHOLE point cleared — a caller released by `fault
            # clear` must not go on to execute other cleared rules
            # from the stale snapshot
            if rule not in self._rules.get(name, ()):
                continue
            if not rule.should_fire():
                continue
            rule.hits += 1
            _INJECTIONS.inc(point=name, action=rule.action)
            if rule.hits == 1:
                # journal the FIRST hit per rule only: per-frame
                # failpoints (a partition black-holing every ping, a
                # delay on every inbound frame) fire many times a
                # second and would evict real transition/failover
                # events from the ring — the volume lives in the
                # fault_injections_total counter instead
                get_journal().record(
                    "fault.injected", point=name, action=rule.action)
            if rule.action == "crash":
                _crash_now(name, rule)
            elif rule.action == "delay":
                d = rule.delay
                if rule.jitter:
                    d += random.random() * rule.jitter
                await asyncio.sleep(d)
            elif rule.action == "stall":
                log.warning("failpoint %s stalled (rule %d; release "
                            "with fault clear)", name, rule.rule_id)
                await rule._cleared.wait()
            elif rule.action == "error":
                raise resolve_error(rule.error)(
                    "injected fault at %s" % name)
            elif rule.action == "drop":
                verdict = "drop"
        return verdict


def _write_crash_fingerprint(name: str, rule: FaultRule) -> None:
    """Best-effort crash breadcrumb for the forensics plane: a process
    about to die takes its in-memory journal with it, so when
    ``MANATEE_CRASH_DIR`` points somewhere, drop one small JSON file
    naming the seam, variant, and the exit status the parent is about
    to observe.  The incident analyzer (obs/incident.py) reads these
    to turn an opaque ``exit 86`` / SIGKILL into a named root cause.
    Fully fenced: fingerprinting must never keep a crash from
    crashing."""
    try:
        crash_dir = os.environ.get("MANATEE_CRASH_DIR")
        if not crash_dir:
            return
        import json as _json

        from manatee_tpu.obs.causal import hlc_now
        from manatee_tpu.obs.journal import get_journal as _gj
        ts = time.time()
        fp = {
            "kind": "crash",
            "point": name,
            "action": "crash",
            "variant": rule.variant,
            "ts": round(ts, 6),
            "hlc": hlc_now(),
            "peer": _gj().peer,
            "pid": os.getpid(),
            "status": (-signal.SIGKILL if rule.variant == "kill"
                       else CRASH_EXIT_CODE),
        }
        path = os.path.join(crash_dir,
                            "crash-%d-%d.json" % (os.getpid(),
                                                  int(ts * 1000)))
        with open(path, "w") as f:
            f.write(_json.dumps(fp))
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        pass


def _crash_now(name: str, rule: FaultRule) -> None:
    """Terminate THIS process at the seam, un-catchably.  ``exit`` is a
    hard ``os._exit`` — no exception propagation, no finally blocks, no
    atexit, no daemon signal handlers, exactly the guarantee the crash
    sweep needs (a crash a supervisor could observe as a clean shutdown
    would not be a crash).  ``kill`` raises SIGKILL against ourselves:
    the kernel path, indistinguishable from an OOM kill to the parent.
    The log line is best-effort breadcrumb only — the whole point is
    that nothing after this instant is guaranteed to run."""
    log.critical("failpoint %s: crashing the process (variant=%s, "
                 "rule %d)", name, rule.variant, rule.rule_id)
    _write_crash_fingerprint(name, rule)
    try:
        sys.stderr.flush()
        sys.stdout.flush()
    except Exception:
        pass
    if rule.variant == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        # SIGKILL is delivered on return to user mode; never fall
        # through to executing the seam if delivery lags a tick
        while True:                                # pragma: no cover
            time.sleep(1)
    os._exit(CRASH_EXIT_CODE)


# ---- process singleton ----

_REGISTRY: FaultRegistry | None = None

# Runtime-arming gate: POST/DELETE /faults are refused (403) unless
# fault injection was explicitly enabled for this process — via
# MANATEE_FAULTS_ENABLED=1, by ACTUALLY arming something at boot
# (MANATEE_FAULTS or a config `faults` list: arm_specs calls
# enable_http only when a spec armed — the mere presence of a refused
# typo'd spec must not open the surface), or by a config
# `faultsEnabled: true` (what the test harness sets).  Without the
# gate every production daemon would ship an unauthenticated
# wedge-this-shard endpoint on ports dashboards already reach.
# GET stays open: listing armed rules and the catalog is read-only
# introspection like /metrics.
_HTTP_ENABLED = bool(os.environ.get("MANATEE_FAULTS_ENABLED"))


def enable_http() -> None:
    """Opt this process into runtime fault arming (config wiring)."""
    global _HTTP_ENABLED
    _HTTP_ENABLED = True


def http_arming_enabled() -> bool:
    return _HTTP_ENABLED


def get_faults() -> FaultRegistry:
    """The process-wide registry (created on first use)."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = FaultRegistry()
    return _REGISTRY


async def point(name: str) -> str:
    """THE failpoint API: call at an I/O seam with a cataloged name.
    Returns ``"ok"`` (proceed) or ``"drop"`` (the call site applies its
    documented black-hole behavior); may sleep, stall, or raise per the
    armed rules.  With nothing armed this is a None check."""
    reg = _REGISTRY
    if reg is None or not reg._rules:
        return "ok"
    return await reg.fire(name)


def _rule_signature(kw: dict) -> tuple:
    """Dedup key over parsed-spec kwargs AND listed-rule dicts (the
    latter null out fields irrelevant to the action — normalize both
    shapes to the arm() defaults)."""
    return (kw["point"], kw["action"],
            kw.get("error") or "FaultError",
            kw.get("variant") or "exit",
            kw.get("delay") or 0.0, kw.get("jitter") or 0.0,
            kw.get("count"), kw.get("prob"))


def arm_specs(specs, *, source: str) -> int:
    """Arm a batch of spec strings (config/env boot path).  Bad specs
    are logged and skipped — a typo in a drill config must not keep an
    HA daemon from booting.  A spec identical to an already-armed live
    rule is skipped too: MANATEE_FAULTS and a config `faults` list
    naming the same spec must not stack two rules and inject double
    what the operator asked for.  Boot-time arming is the opt-in: it
    also enables the runtime POST/DELETE surface."""
    reg = get_faults()
    live = {_rule_signature(r) for r in reg.list()
            if not r["exhausted"]}
    n = 0
    for spec in specs or ():
        try:
            kw = parse_spec(str(spec))
            sig = _rule_signature(kw)
            if sig in live:
                log.warning("fault spec %r already armed at boot; "
                            "not stacking a duplicate", spec)
                continue
            reg.arm(source=source, **kw)
            live.add(sig)
            n += 1
        except FaultSpecError as e:
            log.error("ignoring bad fault spec %r: %s", spec, e)
    if n:
        # only ACTUAL arming is the opt-in: a config whose every spec
        # was refused must not leave the runtime surface open while
        # the operator believes fault injection failed to engage
        enable_http()
    return n


def _arm_from_env() -> None:
    env = os.environ.get("MANATEE_FAULTS")
    if env:
        arm_specs([s for s in env.split(";") if s.strip()],
                  source="env")


_arm_from_env()


# ---- HTTP glue (shared by the status server, the backup REST server,
# and coordd's metrics listener — aiohttp stays out of this module) ----

_DISABLED_MSG = ("runtime fault arming is disabled on this daemon; "
                 "enable with MANATEE_FAULTS_ENABLED=1 (or the "
                 "`faultsEnabled` config key) and restart")


def http_list_reply() -> tuple[dict, int]:
    """GET /faults payload: armed rules + the full catalog."""
    return ({
        "armed": get_faults().list(),
        "arming_enabled": http_arming_enabled(),
        "catalog": {name: {"desc": ent[0], "actions": list(ent[2])}
                    for name, ent in sorted(CATALOG.items())},
    }, 200)


def http_arm_reply(body) -> tuple[dict, int]:
    """POST /faults body: ``{"spec": "..."}"``, ``{"specs": [...]}``,
    or explicit fields ``{"point":..., "action":..., ...}``."""
    if not http_arming_enabled():
        return {"error": _DISABLED_MSG}, 403
    if not isinstance(body, dict):
        return {"error": "body must be a JSON object"}, 400
    specs: list[str] = []
    if isinstance(body.get("spec"), str):
        specs.append(body["spec"])
    for s in body.get("specs") or []:
        if isinstance(s, str):
            specs.append(s)
    armed = []
    try:
        if specs:
            # validate EVERY spec before arming ANY: a typo in a batch
            # (e.g. a two-spec partition drill) must not leave the
            # target half-armed with nothing reporting it
            parsed = [validate_spec(s) for s in specs]
            for kw in parsed:
                armed.append(get_faults().arm(source="http", **kw))
        elif body.get("point"):
            kw = {k: body[k]
                  for k in ("point", "action", "error", "delay",
                            "jitter", "count", "prob", "variant")
                  if k in body}
            armed.append(get_faults().arm(source="http", **kw))
        else:
            return {"error": "provide spec/specs or point+action"}, 400
    except FaultSpecError as e:
        return {"error": str(e)}, 400
    except (TypeError, ValueError) as e:
        return {"error": "bad arm request: %s" % e}, 400
    return {"armed": [r.to_dict() for r in armed]}, 200


def http_clear_reply(query) -> tuple[dict, int]:
    """DELETE /faults[?point=NAME][&id=N] — no params clears all."""
    if not http_arming_enabled():
        return {"error": _DISABLED_MSG}, 403
    pt = query.get("point") or None
    if pt is not None and pt not in CATALOG:
        # same typo protection as arming, on BOTH surfaces: a 200
        # {"cleared": 0} for a misspelled heal would leave the fault
        # armed with the operator believing it healed
        return {"error": "unknown failpoint %r" % pt}, 400
    rid = query.get("id")
    try:
        rid = int(rid) if rid not in (None, "") else None
    except ValueError:
        return {"error": "id must be an integer"}, 400
    n = get_faults().clear(pt, rule_id=rid)
    return {"cleared": n}, 200


def attach_http(app) -> None:
    """Register ``GET/POST/DELETE /faults`` on an aiohttp application —
    the one runtime arming surface, shared verbatim by the status
    server, the backup REST server, and coordd's metrics listener (each
    arms the registry of its OWN process)."""
    from aiohttp import web

    async def faults_get(_req):
        body, status = http_list_reply()
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def faults_post(req):
        try:
            payload = await req.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            payload = None
        body, status = http_arm_reply(payload)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def faults_delete(req):
        body, status = http_clear_reply(req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    app.router.add_get("/faults", faults_get)
    app.router.add_post("/faults", faults_post)
    app.router.add_delete("/faults", faults_delete)


__all__ = [
    "ACTIONS",
    "CATALOG",
    "CRASH_EXIT_CODE",
    "CRASH_VARIANTS",
    "FaultError",
    "FaultRegistry",
    "FaultRule",
    "FaultSpecError",
    "arm_specs",
    "attach_http",
    "enable_http",
    "get_faults",
    "http_arming_enabled",
    "http_arm_reply",
    "http_clear_reply",
    "http_list_reply",
    "parse_spec",
    "point",
    "resolve_error",
    "validate_arm",
    "validate_spec",
]
