"""Fault-injection call-site discipline.

The failpoint catalog (:mod:`manatee_tpu.faults.catalog`) is the single
source of truth for which seams exist; a ``faults.point("...")`` whose
name is not there can never be armed (typos silently never fire), and a
name reused across seams makes arming ambiguous.  This rule keeps call
sites honest:

- the first argument must be a string literal (a computed name defeats
  both this rule and the catalog's typo protection);
- the literal must be a cataloged point name;
- within one file a point name may be invoked once (one seam, one
  name); the catalog additionally binds each name to the file(s)
  allowed to invoke it, which is what makes names unique TREE-wide —
  a second file borrowing a name is flagged here.

The file-binding check applies to production sources (paths under
``manatee_tpu/``); lint fixtures and tests exercise the other checks
with arbitrary paths.
"""

from __future__ import annotations

import ast

from manatee_tpu.lint.engine import FileContext, dotted, rule

RULE = "faultpoint-unregistered"


def _is_point_call(name: str | None) -> bool:
    return name is not None and (name == "faults.point"
                                 or name.endswith(".faults.point"))


@rule(RULE, "faults.point() names must be literal, cataloged, and "
            "unique to their seam")
def faultpoint_unregistered(ctx: FileContext):
    from manatee_tpu.faults.catalog import CATALOG, files_for

    seen: dict[str, int] = {}
    path = ctx.path.replace("\\", "/")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) \
                or not _is_point_call(dotted(node.func)):
            continue
        arg = node.args[0] if node.args else None
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            yield ctx.finding(
                node.lineno, RULE,
                "faults.point() takes a string-literal point name "
                "(computed names defeat the catalog's typo "
                "protection)")
            continue
        pt = arg.value
        if pt not in CATALOG:
            yield ctx.finding(
                node.lineno, RULE,
                "failpoint %r is not in the catalog "
                "(manatee_tpu/faults/catalog.py) — it can never be "
                "armed" % pt)
            continue
        if pt in seen:
            yield ctx.finding(
                node.lineno, RULE,
                "failpoint %r already invoked at line %d in this "
                "file (one seam, one name)" % (pt, seen[pt]))
        else:
            seen[pt] = node.lineno
        if "manatee_tpu/" in path \
                and not any(path.endswith(f) for f in files_for(pt)):
            yield ctx.finding(
                node.lineno, RULE,
                "failpoint %r is registered to %s, not this file "
                "(names are bound to their seam)"
                % (pt, ", ".join(files_for(pt))))
