"""Flow-sensitive async rules over per-function CFGs (lint/cfg.py).

The event-loop concurrency model gives every coroutine a free mutual
exclusion guarantee: between two await points nobody else runs.  All
three rules here police the places where that guarantee silently ends
— an ``await`` inside a window that looked atomic:

- ``atomic-section-broken``: a load-modify-save of shared state with an
  await between the load and the save (the torn-meta bug class: a
  concurrent writer's save lands during the await and this save then
  reinstates stale state).  Declared ``# mnt-lint: atomic-section``
  regions are verified await-free; load/save pairs are also inferred
  from data flow.
- ``lockset-inconsistent``: Eraser-style lockset inference per
  attribute — an attribute the class guards with ``async with
  self._lock`` at several sites, written elsewhere across an await
  without it, breaks the very interleavings the lock exists to stop.
- ``cancel-unsafe-acquire``: a resource-acquiring call whose handle is
  still unprotected (no context manager, no try/finally, no ownership
  transfer) at the next await point — a cancellation landing there
  leaks the handle forever (the PR 8 listening-socket leak class).
"""

from __future__ import annotations

import ast
import fnmatch

from manatee_tpu.lint.cfg import (
    AWAIT,
    CALL,
    HIT,
    KEEP,
    LOAD,
    LOAD_NAME,
    STOP,
    STORE,
    STORE_NAME,
    scan_paths,
)
from manatee_tpu.lint.engine import (
    FileContext,
    allow_matches,
    dotted,
    rule,
    walk_no_defs,
)
from manatee_tpu.lint.summaries import CLOSE_METHODS

RULE_ATOMIC = "atomic-section-broken"
RULE_LOCKSET = "lockset-inconsistent"
RULE_CANCEL = "cancel-unsafe-acquire"

_AWAIT_NODES = (ast.Await, ast.AsyncFor, ast.AsyncWith)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


# ---------------------------------------------------------------- helpers

def _lock_withs(ctx: FileContext, node) -> list:
    """(with-stmt, lock names) for every enclosing ``with``/``async
    with`` over plain dotted expressions, innermost first."""
    out = []
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(cur, _FUNC_NODES):
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            names = frozenset(
                d for item in cur.items
                if (d := dotted(item.context_expr)) is not None)
            if names:
                out.append((cur, names))
        cur = ctx.parents.get(cur)
    return out


def _shares_lock_stmt(ctx: FileContext, a, b) -> bool:
    """True when one dotted-CM with statement lexically encloses both
    *a* and *b* — a lock provably held across the whole window."""
    held = {id(w) for w, _ in _lock_withs(ctx, a)}
    return any(id(w) in held for w, _ in _lock_withs(ctx, b))


def _mentions(node, names: set) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id in names
               for sub in ast.walk(node))


def _glob_stem(name: str, globs) -> str | None:
    """Strip a matching glob's literal core out of *name*, pairing
    '_load_meta' (via '*load*') with '_save_meta' (via '*save*') on
    the shared '__meta' stem."""
    for g in globs:
        if fnmatch.fnmatch(name, g):
            core = g.replace("*", "")
            if core and core in name:
                return name.replace(core, "", 1)
            return name
    return None


# ------------------------------------------- interprocedural plumbing
#
# Every helper below degrades to the v3 behavior when ctx.summaries is
# None (interprocedural analysis off) or a call does not resolve: an
# opaque call keeps the sound default the per-function rules always
# assumed.

def _suspend_filter(ctx: FileContext, fn):
    """scan_paths ``suspends`` callable: ``await helper()`` of a
    project coroutine whose summary proves it never suspends runs
    inline — no other task can interleave there."""
    db = ctx.summaries
    if db is None:
        return None

    def suspends(e) -> bool:
        node = e.node
        if isinstance(node, ast.Await) \
                and isinstance(node.value, ast.Call):
            name = dotted(node.value.func)
            if name is not None:
                s = db.resolve_call(ctx.path, fn, name)
                if s is not None and s.is_async and not s.may_suspend:
                    return False
        return True

    return suspends


def _await_suspends(ctx: FileContext, fn, node) -> bool:
    """AST-level twin of :func:`_suspend_filter` for rules that walk
    the tree instead of the CFG."""
    db = ctx.summaries
    if db is None or not isinstance(node, ast.Await) \
            or not isinstance(node.value, ast.Call):
        return True
    name = dotted(node.value.func)
    if name is None:
        return True
    s = db.resolve_call(ctx.path, fn, name)
    return not (s is not None and s.is_async and not s.may_suspend)


def _callee_params(ctx: FileContext, summary) -> tuple:
    fd = ctx.summaries.graph.defs.get(summary.fqn) \
        if ctx.summaries is not None else None
    return fd.params if fd is not None else ()


def _map_arg0(ctx: FileContext, summary, call, spec):
    """A callee-side first-argument spec (``["param", name]`` /
    ``["dump", ast-dump]``) translated into the caller's frame: the
    ast.dump of the caller expression, or None when unmappable (the
    pair check is then skipped — sound, may over-match)."""
    if spec is None:
        return None
    kind, payload = spec
    if kind == "dump":
        return payload
    params = _callee_params(ctx, summary)
    if payload in params:
        pos = params.index(payload)
        if pos < len(call.args):
            return ast.dump(call.args[pos])
    for kw in call.keywords:
        if kw.arg == payload:
            return ast.dump(kw.value)
    return None


# ----------------------------------------------------- atomic-section-broken

@rule(RULE_ATOMIC,
      "load-modify-save of shared state spans an await point")
def atomic_section_broken(ctx: FileContext):
    """Two halves.  Declared: a ``# mnt-lint: atomic-section`` region
    asserts no await point inside — the machine-checked form of the
    prose invariants dirstore._save_meta and coordd's snapshot pairing
    used to carry as comments.  Inferred: a local loaded from
    ``self.X``/module state (or a ``*load*`` method call) that flows
    into a save of the same state with an await on some path between
    them — unless one dotted ``with``/``async with`` (a lock) spans the
    whole window, or the local is re-loaded after the await."""
    yield from _atomic_declared(ctx)
    yield from _atomic_inferred(ctx)


def _atomic_declared(ctx: FileContext):
    for begin, end, label in ctx.annotations:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, _AWAIT_NODES):
                continue
            line = getattr(node, "lineno", 0)
            if begin <= line <= end:
                owner = ctx.owners.get(node)
                if owner is not None and owner.lineno > begin:
                    # the await belongs to a def nested INSIDE the
                    # region: it runs when that function is later
                    # called, not while the section executes (the CFG
                    # layer treats nested defs as opaque for the same
                    # reason)
                    continue
                if not _await_suspends(ctx, owner, node):
                    continue      # proven-inline helper: still atomic
                what = {ast.Await: "await",
                        ast.AsyncFor: "async for",
                        ast.AsyncWith: "async with"}[type(node)]
                yield ctx.finding(
                    line, RULE_ATOMIC,
                    "atomic section%s declared at line %d is broken by "
                    "this %s: another task can interleave here and the "
                    "section's load-to-save window is no longer atomic"
                    % (" %r" % label if label else "", begin, what))


def _state_of(ctx: FileContext, fn, value, local_names: set,
              declared_globals: set):
    """What shared state an assignment's RHS reads, if any."""
    if isinstance(value, ast.Attribute):
        d = dotted(value)
        if d and d.startswith("self."):
            return ("attr", d)
        return None
    if isinstance(value, ast.Name):
        # module state: a `global`-declared name, or a module-level
        # binding the function never shadows with a local store
        if value.id in ctx.module_globals \
                and (value.id in declared_globals
                     or value.id not in local_names):
            return ("global", value.id)
        return None
    call = value.value if isinstance(value, ast.Await) else value
    if isinstance(call, ast.Call) and isinstance(call.func, ast.Attribute):
        recv = dotted(call.func.value)
        stem = _glob_stem(call.func.attr, ctx.config.atomic_load_calls)
        if recv is not None and stem is not None:
            arg0 = ast.dump(call.args[0]) if call.args else None
            return ("loadcall", recv, stem, arg0)
    # a helper that RETURNS a *load* read (summary load_returns): the
    # assignment is a load of that state one call level down
    if isinstance(call, ast.Call) and ctx.summaries is not None:
        name = dotted(call.func)
        s = ctx.summaries.resolve_call(ctx.path, fn, name) \
            if name is not None else None
        if s is not None and s.load_returns \
                and (not s.is_async or isinstance(value, ast.Await)):
            lr = s.load_returns[0]
            return ("loadcall", lr["recv"], lr["stem"],
                    _map_arg0(ctx, s, call, lr["arg0"]))
    return None


def _save_anchors(ctx: FileContext, fn, state, local: str) -> dict:
    """id(event-anchor-node) -> (line, description) for statements in
    *fn* that save *state* using the loaded value *local*."""
    out: dict[int, tuple] = {}
    owners = ctx.owners
    for node in walk_no_defs(fn):
        if owners.get(node) is not fn:
            continue
        if state[0] in ("attr", "global"):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            else:
                continue
            if not _mentions(value, {local}):
                continue
            for t in targets:
                if state[0] == "attr" and isinstance(t, ast.Attribute) \
                        and dotted(t) == state[1]:
                    out[id(t)] = (t.lineno, state[1])
                elif state[0] == "global" and isinstance(t, ast.Name) \
                        and t.id == state[1]:
                    out[id(t)] = (t.lineno, state[1])
        else:                    # loadcall
            _, recv, stem, arg0 = state
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and dotted(node.func.value) == recv:
                save_stem = _glob_stem(node.func.attr,
                                       ctx.config.atomic_save_calls)
                if save_stem is None or save_stem != stem:
                    pass
                else:
                    args = list(node.args) + [kw.value
                                              for kw in node.keywords]
                    if not any(_mentions(a, {local}) for a in args):
                        continue
                    if arg0 is not None and node.args \
                            and ast.dump(node.args[0]) != arg0:
                        continue   # a different dataset/key
                    out[id(node)] = (node.lineno,
                                     "%s.%s(...)" % (recv,
                                                     node.func.attr))
                    continue
            hit = _helper_save(ctx, fn, node, recv, stem, arg0, local)
            if hit is not None:
                out[id(node)] = hit
    return out


def _helper_save(ctx: FileContext, fn, call, recv, stem, arg0,
                 local: str):
    """Does *call* resolve to a helper whose summary performs the
    matching ``*save*`` of (*recv*, *stem*) with the loaded *local*
    flowing into the saved value?  (line, description) when yes."""
    db = ctx.summaries
    if db is None:
        return None
    name = dotted(call.func)
    if name is None:
        return None
    s = db.resolve_call(ctx.path, fn, name)
    if s is None or not s.save_calls:
        return None
    if s.is_async and not isinstance(ctx.parents.get(call), ast.Await):
        return None              # un-awaited coroutine: nothing ran
    params = _callee_params(ctx, s)
    for sc in s.save_calls:
        if sc["stem"] != stem or sc["recv"] != recv:
            continue
        # the loaded value must flow into a save-value parameter
        feeds = False
        for pname in sc["value_params"]:
            if pname in params:
                pos = params.index(pname)
                if pos < len(call.args) \
                        and _mentions(call.args[pos], {local}):
                    feeds = True
            for kw in call.keywords:
                if kw.arg == pname and _mentions(kw.value, {local}):
                    feeds = True
        if not feeds:
            continue
        helper_arg0 = _map_arg0(ctx, s, call, sc["arg0"])
        if arg0 is not None and helper_arg0 is not None \
                and helper_arg0 != arg0:
            continue             # a different dataset/key: not this pair
        return (call.lineno,
                "%s.%s (via %s)" % (recv, stem.strip("_") or "state",
                                    name))
    return None


def _atomic_inferred(ctx: FileContext):
    owners = ctx.owners
    for fn, cfg in ctx.cfgs.items():
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        local_names = {e.name for _, _, e in cfg.events()
                       if e.kind == STORE_NAME}
        declared_globals = {n for node in walk_no_defs(fn)
                            if isinstance(node, ast.Global)
                            for n in node.names}
        for node in walk_no_defs(fn):
            if owners.get(node) is not fn \
                    or not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            local = node.targets[0].id
            state = _state_of(ctx, fn, node.value, local_names,
                              declared_globals)
            if state is None:
                continue
            anchors = _save_anchors(ctx, fn, state, local)
            if not anchors:
                continue
            start = cfg.position_of(node.targets[0])
            if start is None:
                continue

            def classify(e, awaited, *, _local=local, _anchors=anchors):
                if id(e.node) in _anchors:
                    # an unawaited save does NOT resolve the window: a
                    # save/await/save sequence still reinstates
                    # pre-await state at the second save, so keep
                    # walking (only a re-load of the local ends it)
                    return HIT if awaited else KEEP
                if e.kind == STORE_NAME and e.name == _local:
                    return STOP   # re-loaded/rebound: a fresh window
                return KEEP

            for e2, _ in scan_paths(cfg, start, classify,
                                    suspends=_suspend_filter(ctx, fn)):
                if _shares_lock_stmt(ctx, node, e2.node):
                    continue      # one lock spans load and save
                line, desc = anchors[id(e2.node)]
                yield ctx.finding(
                    line, RULE_ATOMIC,
                    "load-modify-save of %s spans an await: %r was "
                    "loaded at line %d and an interleaved writer can "
                    "land before this save reinstates the stale value "
                    "— re-load after the await, or hold one lock "
                    "across the whole window"
                    % (desc, local, node.lineno))


# ---------------------------------------------------- lockset-inconsistent

def _first_level(name: str) -> str | None:
    parts = name.split(".")
    if len(parts) >= 2 and parts[0] == "self":
        return "self." + parts[1]
    return None


@rule(RULE_LOCKSET,
      "attribute lock-guarded at some sites, written across an await "
      "without it elsewhere")
def lockset_inconsistent(ctx: FileContext):
    """Eraser's lockset discipline, adapted to the event loop: single
    reads/writes are already atomic here, so only *windows* race — a
    read or write of ``self.X`` followed on some path by a write of
    ``self.X`` with an await between them.  When the class guards X
    with ``async with self.<lock>`` at ``lockset-min-guarded``+ sites,
    any such window not spanned by that lock is exactly the
    interleaving the guarded sites were protecting against."""
    min_guarded = ctx.config.lockset_min_guarded
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        accesses = []            # (key, event, block, idx, cfg)
        lock_attrs: set[str] = set()
        for m in methods:
            cfg = ctx.cfgs.get(m)
            if cfg is None:
                continue
            for b in cfg.blocks:
                lock_attrs.update(
                    name for name in b.locks if name.startswith("self."))
            for b, i, e in cfg.events():
                if e.kind in (LOAD, STORE) and e.name:
                    key = _first_level(e.name)
                    if key is not None:
                        accesses.append((key, e, b, i, cfg))
        guard_sites: dict[tuple, set] = {}
        for key, e, b, i, cfg in accesses:
            if key in lock_attrs:
                continue
            for lock in b.locks:
                if lock.startswith("self."):
                    guard_sites.setdefault((key, lock), set()).add(e.line)
        guarding: dict[str, set] = {}
        for (key, lock), lines in guard_sites.items():
            if len(lines) >= min_guarded:
                guarding.setdefault(key, set()).add(lock)
        reported: set[tuple] = set()
        for key, e1, b1, i1, cfg in accesses:
            locks = guarding.get(key)
            if not locks:
                continue
            req = frozenset()
            if ctx.summaries is not None:
                sm = ctx.summaries.summary_for(ctx.path, cfg.func)
                if sm is not None:
                    # every resolved call site of this private method
                    # provably holds these locks around the call: a
                    # window inside it is already guarded by the
                    # callers (the summary layer's required_held fact)
                    req = sm.required_held
            if locks & req:
                continue

            def classify(e, awaited, *, _key=key, _e1=e1):
                if e.kind == STORE and e.name \
                        and _first_level(e.name) == _key \
                        and e.node is not _e1.node:
                    return HIT if awaited else STOP
                return KEEP

            for e2, _ in scan_paths(
                    cfg, (b1, i1), classify,
                    suspends=_suspend_filter(ctx, cfg.func)):
                pos2 = cfg.position_of(e2.node)
                locks2 = pos2[0].locks if pos2 else frozenset()
                if locks & b1.locks & locks2 \
                        and _shares_lock_stmt(ctx, e1.node, e2.node):
                    continue     # guarded continuously across the window
                mark = (key, e2.line)
                if mark in reported:
                    continue
                reported.add(mark)
                lockname = sorted(locks)[0]
                yield ctx.finding(
                    e2.line, RULE_LOCKSET,
                    "%s is guarded by 'async with %s' at %d other "
                    "site(s), but this write ends a window (opened at "
                    "line %d) that crosses an await without it — take "
                    "the lock across the window or document why this "
                    "site cannot race"
                    % (key, lockname,
                       len(guard_sites.get((key, lockname), ())),
                       e1.line))


# --------------------------------------------------- cancel-unsafe-acquire

_ACQ_WRAPPERS = {"wait_for", "shield"}
# shared with the summary layer's resource-escape extraction, so both
# sides agree on what counts as "closing" a handle
_CLOSE_METHODS = CLOSE_METHODS


def _qualname(ctx: FileContext, node) -> str:
    owner = ctx.owners.get(node)
    return owner.name if owner is not None else "<module>"


def _name_match(entries, name: str | None) -> bool:
    if not name:
        return False
    for entry in entries:
        if "." in entry:
            if name == entry:
                return True
        elif name == entry or name.endswith("." + entry):
            return True
    return False


def _binding_of(ctx: FileContext, call) -> tuple:
    """('with'|'discard'|'handles'|'escape', data) — how the acquire's
    result is bound.  Climbs through await and wait_for/shield
    wrappers to the binding statement."""
    cur = call
    parent = ctx.parents.get(cur)
    while True:
        if isinstance(parent, ast.Await):
            cur, parent = parent, ctx.parents.get(parent)
            continue
        if isinstance(parent, ast.Call):
            pname = dotted(parent.func)
            if pname and pname.rsplit(".", 1)[-1] in _ACQ_WRAPPERS \
                    and cur in parent.args:
                cur, parent = parent, ctx.parents.get(parent)
                continue
        break
    if isinstance(parent, ast.withitem):
        return ("with", None)
    if isinstance(parent, ast.Expr):
        return ("discard", cur)
    if isinstance(parent, ast.Assign) and parent.value is cur \
            and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return ("handles", (parent, [t]))
        if isinstance(t, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in t.elts):
            return ("handles", (parent, list(t.elts)))
    # attribute/subscript targets, return values, nested expressions:
    # ownership moves somewhere this local analysis cannot follow
    return ("escape", None)


def _cleanup_try(ctx: FileContext, node, handles: set | None) -> bool:
    """Is *node* inside a try statement whose finally (or a
    BaseException/CancelledError/bare handler) can clean up?  With
    *handles*, the cleanup must actually mention one of them."""
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(cur, _FUNC_NODES):
        if isinstance(cur, ast.Try):
            bodies = list(cur.finalbody)
            for h in cur.handlers:
                names = set()
                if h.type is not None:
                    for n in (h.type.elts if isinstance(h.type, ast.Tuple)
                              else [h.type]):
                        d = dotted(n)
                        if d:
                            names.add(d.rsplit(".", 1)[-1])
                if h.type is None or names & {"BaseException",
                                              "CancelledError"}:
                    bodies.extend(h.body)
            if bodies:
                if handles is None:
                    return True
                if any(_mentions(s, handles) for s in bodies):
                    return True
        cur = ctx.parents.get(cur)
    return False


def _idempotent_ensure(ctx: FileContext, node) -> bool:
    """A discarded create that is guarded by an existence check
    (``if not await x.exists(...):``) or sits in a try tolerating an
    *ExistsError is an idempotent *ensure*: a cancellation leaves
    convergent state a retry walks straight past, not stranded debris
    (coord mkdirp, the isolate-parent create, the dataset ensure)."""
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(cur, _FUNC_NODES):
        if isinstance(cur, ast.If) and any(
                isinstance(sub, ast.Call)
                and (d := dotted(sub.func)) is not None
                and d.rsplit(".", 1)[-1] == "exists"
                for sub in ast.walk(cur.test)):
            return True
        if isinstance(cur, ast.Try):
            for h in cur.handlers:
                if h.type is None:
                    continue
                for n in (h.type.elts if isinstance(h.type, ast.Tuple)
                          else [h.type]):
                    d = dotted(n)
                    if d and d.rsplit(".", 1)[-1].endswith("ExistsError"):
                        return True
        cur = ctx.parents.get(cur)
    return False


def _protecting_use(ctx: FileContext, fn, name_node) -> bool:
    """A bare-name use of a handle that transfers or guards ownership:
    with-item, return/yield, call argument, stored into an object, or
    aliased to another name.

    v3 treated ANY call argument as an ownership transfer.  With
    summaries, a call resolved to a project function whose parameter
    summary says the handle is *leaked* (never closed, stored, or
    passed on) is NOT a transfer — the window stays open through the
    helper.  Unresolved calls keep the v3 benefit of the doubt."""
    cur, parent = name_node, ctx.parents.get(name_node)
    while parent is not None and not isinstance(parent, ast.stmt):
        if isinstance(parent, ast.withitem):
            return True
        if isinstance(parent, ast.Call) and cur is not parent.func:
            if not _leaky_pass(ctx, fn, parent, cur):
                return True      # passed as an argument: ownership moves
        if isinstance(parent, (ast.Return, ast.Yield)):
            return True
        cur, parent = parent, ctx.parents.get(parent)
    if isinstance(parent, (ast.Return, ast.With, ast.AsyncWith)):
        return True
    if isinstance(parent, ast.Assign) and _mentions(parent.value,
                                                    {name_node.id}):
        return True              # stored/aliased: the new owner cleans up
    return False


def _leaky_pass(ctx: FileContext, fn, call, arg) -> bool:
    """True when *arg* passed to *call* provably does NOT transfer
    ownership: the callee's summary marks that parameter leaked."""
    db = ctx.summaries
    if db is None or arg not in call.args:
        return False
    name = dotted(call.func)
    if name is None:
        return False
    s = db.resolve_call(ctx.path, fn, name)
    if s is None:
        return False
    params = _callee_params(ctx, s)
    pos = call.args.index(arg)
    if pos >= len(params):
        return False
    return s.param_effects.get(params[pos]) == "leaked"


@rule(RULE_CANCEL,
      "acquired resource unprotected at the next await point")
def cancel_unsafe_acquire(ctx: FileContext):
    """Between acquiring a resource and wrapping it in a context
    manager / try-finally, a cancellation landing on any await leaks
    the handle: the CancelledError propagates and nothing ever closes
    it (PR 8: a listening socket leaked forever by a cancel between
    create_server and its guard; a dataset stranded between create and
    the tar spawn).  Flagged when a path from the acquisition reaches
    an await before the handle is protected or ownership moves."""
    config = ctx.config
    db = ctx.summaries
    for fn, cfg in ctx.cfgs.items():
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        susp = _suspend_filter(ctx, fn)
        for b, i, e in list(cfg.events()):
            if e.kind != CALL:
                continue
            handleish = _name_match(config.acquire_calls, e.name)
            discardish = _name_match(config.acquire_discard_calls,
                                     e.name)
            if not handleish and not discardish and db is not None:
                # a helper whose summary RETURNS an acquired handle is
                # itself an acquire: calling it opens the same cancel
                # window the direct call would
                s = db.resolve_call(ctx.path, fn, e.name)
                if s is not None and s.returns_resource and (
                        not s.is_async
                        or isinstance(ctx.parents.get(e.node),
                                      ast.Await)):
                    handleish = True
            if not handleish and not discardish:
                continue
            kind, data = _binding_of(ctx, e.node)
            if kind in ("with", "escape"):
                continue
            if kind == "discard":
                # no handle to track (dataset create): safe only once
                # execution is inside a try that can clean up on cancel
                if not discardish:
                    continue     # a discarded handle-yielder: not ours
                if allow_matches(config.acquire_discard_allow, ctx.path,
                                 _qualname(ctx, e.node)):
                    continue
                if _idempotent_ensure(ctx, e.node):
                    continue

                def classify_discard(ev, awaited):
                    if ev.kind == AWAIT:
                        if susp is not None and not susp(ev):
                            return KEEP   # proven inline: cancel
                                          # cannot land here
                        return STOP if _cleanup_try(ctx, ev.node, None) \
                            else HIT
                    return KEEP

                # scan from the acquire's own await (or the call when
                # not awaited): its own completion is not the window
                start = cfg.position_of(data) or cfg.position_of(e.node)
                hits = scan_paths(cfg, start, classify_discard,
                                  follow_exceptions=False) \
                    if start else []
                if hits:
                    yield ctx.finding(
                        e.line, RULE_CANCEL,
                        "%s(...) acquires a resource with no handle "
                        "bound, and an await is reached at line %d "
                        "before any try that could clean it up on "
                        "cancellation — enter the guarding try/except "
                        "before the next await point"
                        % (e.name, hits[0][0].line))
                continue
            if not handleish:
                continue         # a bound side-effect acquire (znode
                                 # create returning a path): no handle
            assign, name_nodes = data
            handles = {t.id for t in name_nodes}
            start = cfg.position_of(name_nodes[-1])
            if start is None:
                continue

            def classify(ev, awaited, *, _handles=handles, _fn=fn):
                if ev.kind == STORE_NAME and ev.name in _handles:
                    return STOP   # rebound: this window is over
                if ev.kind == LOAD and ev.name:
                    parts = ev.name.split(".")
                    if parts[0] in _handles and len(parts) == 2 \
                            and parts[1] in _CLOSE_METHODS:
                        return STOP   # direct close/transfer call
                    return KEEP
                if ev.kind == LOAD_NAME and ev.name in _handles:
                    return STOP if _protecting_use(ctx, _fn, ev.node) \
                        else KEEP
                if ev.kind == AWAIT:
                    if susp is not None and not susp(ev):
                        return KEEP   # proven inline: cancel cannot
                                      # land here
                    return STOP if _cleanup_try(ctx, ev.node, _handles) \
                        else HIT
                return KEEP

            hits = scan_paths(cfg, start, classify,
                              follow_exceptions=False)
            if hits:
                names = ", ".join(sorted(handles))
                yield ctx.finding(
                    e.line, RULE_CANCEL,
                    "handle(s) %s from %s(...) are unprotected at the "
                    "await on line %d: a cancellation landing there "
                    "leaks the resource — use 'async with'/'with', or "
                    "enter a try/finally that closes them before the "
                    "next await point"
                    % (names, e.name or "acquire", hits[0][0].line))
