"""mnt-lint — the repo's pluggable stdlib static analyzer.

The engine (rule registry, suppression handling, output formats) lives
in :mod:`manatee_tpu.lint.engine`; the rules themselves in
:mod:`manatee_tpu.lint.rules_style` (the original six checks),
:mod:`manatee_tpu.lint.rules_async` (async-concurrency discipline:
orphaned tasks, blocking calls, swallowed cancellation, unreaped
cancels, lock hygiene, unbounded network waits) and
:mod:`manatee_tpu.lint.rules_flow` (flow-sensitive rules over the
per-function CFGs built by :mod:`manatee_tpu.lint.cfg`: broken atomic
sections, inconsistent locksets, cancellation-unsafe acquisitions).

v4 adds the interprocedural layer: :mod:`manatee_tpu.lint.callgraph`
(project-wide call resolution) and :mod:`manatee_tpu.lint.summaries`
(per-function effect summaries — may-suspend, may-block, lock effects,
resource escape, cancellation swallowing — propagated to fixpoint).
The flow rules consult the summaries to see through helper calls;
:mod:`manatee_tpu.lint.rules_interproc` adds the chain-reporting rules
(``transitive-blocking-in-async``,
``cancellation-swallowed-transitively``) and
:mod:`manatee_tpu.lint.rules_obs` the metric/journal-name ↔
docs/observability.md contract (``obs-name-undocumented``).

``tools/lint`` is a thin shim over :func:`main`; ``python -m
manatee_tpu.lint`` works too.  See docs/lint.md for the rule catalog.
"""

from manatee_tpu.lint.engine import (
    RULES,
    Config,
    Finding,
    LintResult,
    check_paths,
    check_source,
    main,
)

# importing the rule modules populates the registry
from manatee_tpu.lint import rules_style  # noqa: F401  (registration)
from manatee_tpu.lint import rules_async  # noqa: F401  (registration)
from manatee_tpu.lint import rules_faults  # noqa: F401  (registration)
from manatee_tpu.lint import rules_flow  # noqa: F401  (registration)
from manatee_tpu.lint import rules_interproc  # noqa: F401  (registration)
from manatee_tpu.lint import rules_obs  # noqa: F401  (registration)

__all__ = [
    "RULES",
    "Config",
    "Finding",
    "LintResult",
    "check_paths",
    "check_source",
    "main",
    "rules_style",
    "rules_async",
    "rules_faults",
    "rules_flow",
    "rules_interproc",
    "rules_obs",
]
