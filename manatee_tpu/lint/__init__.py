"""mnt-lint — the repo's pluggable stdlib static analyzer.

The engine (rule registry, suppression handling, output formats) lives
in :mod:`manatee_tpu.lint.engine`; the rules themselves in
:mod:`manatee_tpu.lint.rules_style` (the original six checks),
:mod:`manatee_tpu.lint.rules_async` (async-concurrency discipline:
orphaned tasks, blocking calls, swallowed cancellation, unreaped
cancels, lock hygiene, unbounded network waits) and
:mod:`manatee_tpu.lint.rules_flow` (flow-sensitive rules over the
per-function CFGs built by :mod:`manatee_tpu.lint.cfg`: broken atomic
sections, inconsistent locksets, cancellation-unsafe acquisitions).

``tools/lint`` is a thin shim over :func:`main`; ``python -m
manatee_tpu.lint`` works too.  See docs/lint.md for the rule catalog.
"""

from manatee_tpu.lint.engine import (
    RULES,
    Config,
    Finding,
    LintResult,
    check_paths,
    check_source,
    main,
)

# importing the rule modules populates the registry
from manatee_tpu.lint import rules_style  # noqa: F401  (registration)
from manatee_tpu.lint import rules_async  # noqa: F401  (registration)
from manatee_tpu.lint import rules_faults  # noqa: F401  (registration)
from manatee_tpu.lint import rules_flow  # noqa: F401  (registration)

__all__ = [
    "RULES",
    "Config",
    "Finding",
    "LintResult",
    "check_paths",
    "check_source",
    "main",
    "rules_style",
    "rules_async",
    "rules_faults",
    "rules_flow",
]
