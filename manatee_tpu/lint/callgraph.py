"""Project-wide call graph: name/attribute resolution over the package.

:func:`scan_module` extracts one file's *declaration surface* — import
maps, classes/bases/methods, module functions, per-def shape — as a
plain JSON-able dict, and :class:`CallGraph` assembles those dicts into
a resolvable graph.  The split matters for incrementality: declaration
dicts depend only on their own file's content, so the ``--cache`` layer
can persist them per file and rebuild the whole graph from cache
without re-parsing an unchanged tree (resolution itself is always
re-run in memory — it is cross-file by nature and cheap).

Resolution is *bounded and syntactic*: no dataflow, no type inference
beyond what the module text states directly.  What resolves:

- bare names: module-level functions, ``from x import y`` aliases
  (including aliases into other project modules);
- ``mod.func`` / ``alias.func`` where the head is an ``import``-bound
  alias pointing at a project module;
- ``self.meth`` / ``cls.meth``: the enclosing class, then its base
  classes (bases resolved through the same import maps, walk bounded
  by ``_MRO_BOUND``);
- ``Class.meth`` for classes reachable from the same module;
- ``self.attr.meth`` where the class assigns exactly ``self.attr =
  ClassName(...)`` and ``ClassName`` resolves to a project class (one
  attribute level, no chains; an attribute also assigned from anything
  else loses the fact).

Everything else — computed receivers, duck-typed attributes, calls into
the stdlib or site-packages — stays *unresolved*, and the summary layer
(:mod:`manatee_tpu.lint.summaries`) applies sound defaults there: an
unresolved call may do anything the v3 per-function rules already
assumed an opaque call could do, so a resolution failure can only ever
cost precision, never soundness.

:meth:`CallGraph.canonical` additionally maps an *aliased* name back to
its canonical dotted path (``from time import sleep`` makes ``sleep``
canonicalize to ``time.sleep``) so catalog lookups — the blocking-call
lists — see through import renames even when the target is not a
project function.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import PurePosixPath

from manatee_tpu.lint.engine import dotted

# how many classes an MRO walk will visit before giving up
# (pathological diamond hierarchies stay bounded)
_MRO_BOUND = 16


def module_name(path: str) -> str:
    """Dotted module name for a repo-relative *path*.

    ``manatee_tpu/pg/manager.py`` -> ``manatee_tpu.pg.manager``;
    ``manatee_tpu/obs/__init__.py`` -> ``manatee_tpu.obs``; a shebang
    script without ``.py`` (``tools/lint``) keeps its basename.
    """
    p = PurePosixPath(str(path).replace("\\", "/"))
    parts = list(p.parts)
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<root>"


@dataclasses.dataclass
class FuncDef:
    """One function definition somewhere in the project (plain data —
    reconstructible from a cached declaration dict, no AST held)."""
    fqn: str                  # "pkg.mod:Class.meth" / "pkg.mod:func"
    path: str
    module: str
    qualname: str             # "Class.meth", "func", "f.<locals>.g"
    name: str
    line: int
    end_line: int
    is_async: bool
    cls: str | None           # enclosing class name for methods
    params: tuple             # positional params, self/cls stripped


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: str
    bases: list          # dotted base-class names as written
    methods: dict        # name -> FuncDef
    attr_types: dict     # attr -> dotted class name from
                         # `self.attr = ClassName(...)`


class ModuleInfo:
    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.imports: dict[str, str] = {}       # alias -> module path
        self.from_imports: dict[str, str] = {}  # alias -> "mod.attr"
        self.functions: dict[str, FuncDef] = {}
        self.classes: dict[str, ClassInfo] = {}


# ---- per-file declaration scan ----

def _scan_imports(tree: ast.AST, modname: str) -> tuple[dict, dict]:
    imports: dict[str, str] = {}
    from_imports: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname is not None:
                    imports[a.asname] = a.name
                else:
                    imports[a.name.split(".")[0]] = a.name.split(".")[0]
                    if "." in a.name:
                        # `import a.b.c` also makes `a.b.c.f` a legal
                        # spelling of the deep module's attribute
                        imports[a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:       # relative: resolve against the pkg
                base = modname.split(".")
                base = base[:len(base) - node.level]
                src = ".".join(base + ([node.module] if node.module
                                       else []))
            else:
                src = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                from_imports[a.asname or a.name] = \
                    "%s.%s" % (src, a.name) if src else a.name
    return imports, from_imports


def _attr_ctor_types(cls_node: ast.ClassDef) -> dict:
    """``self.attr = ClassName(...)`` assignments anywhere in the
    class: the one attribute-type fact cheap enough to trust."""
    out: dict[str, str] = {}
    ambiguous: set[str] = set()
    for node in ast.walk(cls_node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        attr = t.attr
        if isinstance(node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor and ctor.rsplit(".", 1)[-1][:1].isupper():
                if attr in out and out[attr] != ctor:
                    ambiguous.add(attr)
                out.setdefault(attr, ctor)
                continue
        ambiguous.add(attr)      # assigned from something else too
    for attr in ambiguous:
        out.pop(attr, None)
    return out


def _def_params(node, in_class: bool) -> list[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if in_class and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def scan_module(path: str, tree: ast.AST) -> tuple[dict, dict]:
    """(declaration dict, qualname -> def AST node).

    The dict is JSON-able and content-determined; the node map exists
    only for the caller that just parsed the tree (fact extraction).
    """
    modname = module_name(path)
    imports, from_imports = _scan_imports(tree, modname)
    decl = {"name": modname, "path": str(path), "imports": imports,
            "from_imports": from_imports, "functions": {},
            "classes": {}, "defs": {}}
    nodes: dict[str, ast.AST] = {}

    def add_def(node, qual: list, cls_name: str | None) -> str:
        qualname = ".".join(qual + [node.name])
        decl["defs"][qualname] = {
            "line": node.lineno,
            "end_line": getattr(node, "end_lineno", node.lineno),
            "is_async": isinstance(node, ast.AsyncFunctionDef),
            "cls": cls_name,
            "params": _def_params(node, cls_name is not None),
        }
        nodes[qualname] = node
        return qualname

    def visit(body, qual: list, cls_name: str | None):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qualname = add_def(node, qual, cls_name)
                if cls_name is None and not qual:
                    decl["functions"][node.name] = qualname
                elif cls_name is not None and qual == [cls_name]:
                    decl["classes"][cls_name]["methods"][node.name] = \
                        qualname
                visit(node.body, qual + [node.name, "<locals>"], None)
            elif isinstance(node, ast.ClassDef):
                if not qual:
                    decl["classes"][node.name] = {
                        "bases": [d for b in node.bases
                                  if (d := dotted(b)) is not None],
                        "methods": {},
                        "attr_types": _attr_ctor_types(node),
                    }
                    visit(node.body, [node.name], node.name)
                else:
                    visit(node.body, qual + [node.name], node.name)

    visit(tree.body, [], None)
    return decl, nodes


# ---- the graph ----

class CallGraph:
    """Defs, per-module import/class tables, and call resolution."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.defs: dict[str, FuncDef] = {}
        # (path, lineno, funcname) -> FuncDef: how rules (which parse
        # files independently) find "their" def in the graph
        self._by_loc: dict[tuple, FuncDef] = {}

    def add(self, decl: dict) -> None:
        """Install one :func:`scan_module` declaration dict."""
        path, modname = decl["path"], decl["name"]
        mod = ModuleInfo(modname, path)
        mod.imports = dict(decl["imports"])
        mod.from_imports = dict(decl["from_imports"])
        self.modules[modname] = mod
        made: dict[str, FuncDef] = {}
        for qualname, d in decl["defs"].items():
            fd = FuncDef(
                fqn="%s:%s" % (modname, qualname), path=path,
                module=modname, qualname=qualname,
                name=qualname.rsplit(".", 1)[-1], line=d["line"],
                end_line=d["end_line"], is_async=d["is_async"],
                cls=d["cls"], params=tuple(d["params"]))
            self.defs[fd.fqn] = fd
            self._by_loc[(path, fd.line, fd.name)] = fd
            made[qualname] = fd
        for name, qualname in decl["functions"].items():
            if qualname in made:
                mod.functions[name] = made[qualname]
        for cname, c in decl["classes"].items():
            ci = ClassInfo(cname, modname, list(c["bases"]),
                           {m: made[q] for m, q in c["methods"].items()
                            if q in made},
                           dict(c["attr_types"]))
            mod.classes[cname] = ci

    # -- lookups --

    def def_at(self, path: str, lineno: int,
               name: str) -> FuncDef | None:
        return self._by_loc.get((str(path), lineno, name))

    def _class(self, module: ModuleInfo, name: str) -> ClassInfo | None:
        """A class by (possibly imported) *name* as seen from
        *module*."""
        if name in module.classes:
            return module.classes[name]
        tgt = module.from_imports.get(name)
        if tgt and "." in tgt:
            src, cls_name = tgt.rsplit(".", 1)
            src_mod = self.modules.get(src)
            if src_mod:
                return src_mod.classes.get(cls_name)
        if "." in name:          # "mod.Class" through a module alias
            head, cls_name = name.rsplit(".", 1)
            tgt = module.imports.get(head)
            src_mod = self.modules.get(tgt) if tgt else None
            if src_mod:
                return src_mod.classes.get(cls_name)
        return None

    def _method(self, module: ModuleInfo, cls: ClassInfo,
                meth: str) -> FuncDef | None:
        """*meth* on *cls* or a base class, bounded walk."""
        seen: set[str] = set()
        queue = [(module, cls)]
        steps = 0
        while queue and steps < _MRO_BOUND:
            steps += 1
            mod, c = queue.pop(0)
            key = "%s.%s" % (c.module, c.name)
            if key in seen:
                continue
            seen.add(key)
            if meth in c.methods:
                return c.methods[meth]
            for base in c.bases:
                bc = self._class(mod, base)
                if bc is not None:
                    queue.append((self.modules.get(bc.module, mod), bc))
        return None

    def canonical(self, path: str, name: str | None) -> str | None:
        """*name* with import aliases expanded to the canonical dotted
        path, for catalog lookups (``sleep`` -> ``time.sleep`` after a
        ``from time import sleep``).  Unknown names pass through."""
        if not name:
            return name
        mod = self.modules.get(module_name(path))
        if mod is None:
            return name
        head, _, rest = name.partition(".")
        tgt = mod.from_imports.get(head)
        if tgt is not None:
            return tgt + ("." + rest if rest else "")
        tgt = mod.imports.get(head)
        if tgt is not None and tgt != head:
            return tgt + ("." + rest if rest else "")
        return name

    def resolve(self, caller: FuncDef | None, path: str,
                name: str | None) -> FuncDef | None:
        """The project function a dotted call *name* at a call site in
        (*caller*, *path*) refers to, or None when unresolvable."""
        if not name:
            return None
        mod = self.modules.get(module_name(path))
        if mod is None:
            return None
        parts = name.split(".")
        # self.meth / cls.meth / self.attr.meth
        if parts[0] in ("self", "cls"):
            if caller is None or caller.cls is None:
                return None
            cls = mod.classes.get(caller.cls)
            if cls is None:
                return None
            if len(parts) == 2:
                return self._method(mod, cls, parts[1])
            if len(parts) == 3:
                ctor = cls.attr_types.get(parts[1])
                if ctor:
                    tc = self._class(mod, ctor)
                    if tc is not None:
                        owner = self.modules.get(tc.module, mod)
                        return self._method(owner, tc, parts[2])
            return None
        if len(parts) == 1:
            fd = mod.functions.get(parts[0])
            if fd is not None:
                return fd
            tgt = mod.from_imports.get(parts[0])
            if tgt and "." in tgt:
                src, fn = tgt.rsplit(".", 1)
                src_mod = self.modules.get(src)
                if src_mod:
                    return src_mod.functions.get(fn)
            return None
        # alias.func / alias.Class.meth through a module import
        head, rest = parts[0], parts[1:]
        tgt = mod.imports.get(head) or mod.from_imports.get(head)
        if tgt is not None:
            src_mod = self.modules.get(tgt)
            if src_mod is not None and len(rest) == 1:
                return src_mod.functions.get(rest[0])
            if src_mod is not None and len(rest) == 2:
                tc = src_mod.classes.get(rest[0])
                if tc is not None:
                    return self._method(src_mod, tc, rest[1])
        # Class.meth reachable from this module (static-style call)
        if len(parts) == 2:
            tc = self._class(mod, parts[0])
            if tc is not None:
                owner = self.modules.get(tc.module, mod)
                return self._method(owner, tc, parts[1])
        return None

    def stats(self) -> dict:
        return {"modules": len(self.modules),
                "functions": len(self.defs)}
