"""Async-concurrency rules: the discipline the control plane's safety
rests on, checked mechanically.

The cluster state machine survives adversarial interleavings only if
every spawned task has an owner, cancellation propagates, and no
coroutine wedges the loop or waits forever on a peer that will never
answer.  Each rule below encodes one of those invariants; docs/lint.md
has the bad/good example pairs.
"""

from __future__ import annotations

import ast

from manatee_tpu.lint.engine import (
    FileContext,
    allow_matches,
    dotted,
    has_await,
    rule,
    walk_no_defs,
)
from manatee_tpu.lint.summaries import (
    BLOCKING_CALLS,
    BLOCKING_IO_CALLS,
    BLOCKING_IO_METHODS,
)

# ---------------------------------------------------------------- spawn

_LOOP_FACTORIES = ("get_event_loop", "get_running_loop", "new_event_loop")


def _spawn_kind(call: ast.Call) -> str | None:
    """'ensure' / 'create' when *call* spawns a free-running task.

    ``TaskGroup.create_task`` results are owned by the group, so only
    ``asyncio.create_task``, a bare ``create_task``, and
    ``<...loop>.create_task`` count as ownerless spawns.
    """
    func = call.func
    name = dotted(func)
    if name is not None:
        last = name.rsplit(".", 1)[-1]
        if last == "ensure_future":
            return "ensure"
        if last == "create_task":
            if name in ("create_task", "asyncio.create_task"):
                return "create"
            recv = name.rsplit(".", 1)[0].rsplit(".", 1)[-1]
            if recv.endswith("loop"):
                return "create"
        return None
    # asyncio.get_event_loop().create_task(...)
    if isinstance(func, ast.Attribute) and func.attr == "create_task" \
            and isinstance(func.value, ast.Call):
        inner = dotted(func.value.func)
        if inner and inner.rsplit(".", 1)[-1] in _LOOP_FACTORIES:
            return "create"
    return None


@rule("orphan-task", "spawned task with no handle (exception lost)")
def orphan_task(ctx: FileContext):
    """A ``create_task`` result that is never bound loses its exception
    forever (and, pre-3.8-semantics aside, the task itself can be
    garbage-collected mid-flight).  ``asyncio.ensure_future`` is flagged
    outright: every call site in this tree spawns a coroutine, and
    ``asyncio.create_task`` is the Python >= 3.7 idiom for that."""
    parents = ctx.parents
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _spawn_kind(node)
        if kind == "ensure":
            yield ctx.finding(
                node.lineno, "orphan-task",
                "asyncio.ensure_future() is retired here: spawn with "
                "asyncio.create_task() and keep the handle")
        elif kind == "create" and isinstance(parents.get(node), ast.Expr):
            yield ctx.finding(
                node.lineno, "orphan-task",
                "task is spawned and discarded: bind the handle (and "
                "cancel/await it on teardown) or its exception is lost")


# ------------------------------------------------- blocking-call-in-async

# the blocking-call catalogs live in the summary layer (summaries.py)
# so the per-call rules here, the transitive may-block propagation, and
# the runtime stall cross-check (obs/profile.py) can never disagree on
# what counts as blocking
_BLOCKING_CALLS = BLOCKING_CALLS
_BLOCKING_IO_CALLS = BLOCKING_IO_CALLS
_BLOCKING_IO_METHODS = BLOCKING_IO_METHODS


def _sync_calls_in_async(ctx: FileContext):
    """Calls inside an async def's own execution context that are not
    themselves awaited (an awaited call is an async API)."""
    owners = ctx.owners
    parents = ctx.parents
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and isinstance(owners.get(node), ast.AsyncFunctionDef) \
                and not isinstance(parents.get(node), ast.Await):
            yield node


def _canonical(ctx: FileContext, name: str | None) -> str | None:
    """Import aliases expanded (``sleep`` -> ``time.sleep`` after a
    ``from time import sleep``) when summaries are available; the raw
    dotted name otherwise (the v3 behavior)."""
    if name is None or ctx.summaries is None:
        return name
    return ctx.summaries.canonical(ctx.path, name)


@rule("blocking-call-in-async", "sync sleep/subprocess/DNS in async def")
def blocking_call_in_async(ctx: FileContext):
    """A synchronous sleep, subprocess wait, or DNS/TCP setup inside
    ``async def`` stalls the whole event loop for its full duration —
    on a sitter that means every health check, watch handler, and RPC
    on the peer.  Use the asyncio equivalent, or push the call into a
    worker thread (``loop.run_in_executor`` / ``asyncio.to_thread``)."""
    blocking = _BLOCKING_CALLS | ctx.config.blocking_extra
    for node in _sync_calls_in_async(ctx):
        name = _canonical(ctx, dotted(node.func))
        if name in blocking:
            yield ctx.finding(
                node.lineno, "blocking-call-in-async",
                "%s() blocks the event loop; use the asyncio "
                "equivalent or run_in_executor/to_thread" % name)


@rule("blocking-io-in-async", "sync file I/O in async def")
def blocking_io_in_async(ctx: FileContext):
    """Sync file I/O (``open``, ``Path.read_text`` & friends) inside
    ``async def`` rides on disk latency: instant on a healthy local
    disk, a multi-second loop stall on a degraded one — exactly when
    the control plane most needs to stay responsive.  Production code
    pushes these into a worker thread; test/bench code disables the
    rule via the ``path-disable`` config (tiny fixture writes do not
    need a thread hop)."""
    for node in _sync_calls_in_async(ctx):
        name = _canonical(ctx, dotted(node.func))
        if name in _BLOCKING_IO_CALLS:
            yield ctx.finding(
                node.lineno, "blocking-io-in-async",
                "%s() is synchronous file I/O; run it in a worker "
                "thread (run_in_executor/to_thread)" % name)
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _BLOCKING_IO_METHODS:
            yield ctx.finding(
                node.lineno, "blocking-io-in-async",
                ".%s() is synchronous file I/O; run it in a worker "
                "thread (run_in_executor/to_thread)" % node.func.attr)


# ------------------------------------------------- swallowed-cancellation

_GENERIC = {"Exception", "BaseException"}


def _handler_names(h: ast.ExceptHandler) -> set:
    """Last components of the exception types a handler catches
    (empty set for a bare ``except:``)."""
    if h.type is None:
        return set()
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = set()
    for n in nodes:
        name = dotted(n)
        if name:
            out.add(name.rsplit(".", 1)[-1])
    return out


def _reraises(h: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for s in h.body
               for n in walk_no_defs(s))


@rule("swallowed-cancellation",
      "generic except in async def eats CancelledError")
def swallowed_cancellation(ctx: FileContext):
    """Cancellation surfaces at await points as ``CancelledError``; a
    generic handler that neither re-raises nor follows an explicit
    ``except asyncio.CancelledError`` arm turns a cancel into a silent
    wedge (the task keeps running, its canceller hangs).  Catching
    CancelledError *mixed into a tuple* with other types is flagged too:
    give cancellation its own arm so the reader can see the decision."""
    owners = ctx.owners
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        if not isinstance(owners.get(node), ast.AsyncFunctionDef):
            continue
        if not has_await(node.body):
            continue           # no await point: cancellation cannot land
        cancel_armed = False
        for h in node.handlers:
            names = _handler_names(h)
            if names and names <= {"CancelledError"}:
                cancel_armed = True      # explicit, deliberate arm
                continue
            if "CancelledError" in names:
                yield ctx.finding(
                    h.lineno, "swallowed-cancellation",
                    "CancelledError is caught in a tuple with %s: give "
                    "cancellation its own except arm"
                    % ", ".join(sorted(names - {"CancelledError"})))
                cancel_armed = True      # it IS handled, however badly
                continue
            generic = h.type is None or (names & _GENERIC)
            if not generic or cancel_armed or _reraises(h):
                continue
            caught = ", ".join(sorted(names & _GENERIC)) or "everything"
            yield ctx.finding(
                h.lineno, "swallowed-cancellation",
                "except %s around awaits can swallow task cancellation: "
                "add 'except asyncio.CancelledError: raise' before it"
                % caught)


# --------------------------------------------------- cancel-without-await

_WAIT_FUNCS = {"gather", "wait", "wait_for", "shield", "as_completed"}


def _attr_names_in(node) -> set:
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


def _is_wait_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    return bool(name) and name.rsplit(".", 1)[-1] in _WAIT_FUNCS


def _function_nodes(tree):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _local_scan(fn, ctx: FileContext):
    """Per-scope maps: name->attr aliases, loop-var->attrs, plus
    spawned/awaited/cancelled local names and awaited/cancelled attrs."""
    alias: dict[str, str] = {}
    loopvars: dict[str, set] = {}
    spawned_locals: set = set()
    awaited_names: set = set()
    cancelled: list = []       # (local name | None, attr | None, lineno)
    owners = ctx.owners
    scope = fn if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        else None
    for node in ast.walk(fn):
        if owners.get(node) is not scope and node is not fn:
            continue
        if isinstance(node, ast.Assign):
            targets, values = node.targets, [node.value]
            if len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(node.targets[0].elts) == len(node.value.elts):
                targets = node.targets[0].elts
                values = node.value.elts
            for t, v in zip(targets, values):
                if not isinstance(t, ast.Name):
                    continue
                if isinstance(v, ast.Attribute):
                    alias[t.id] = v.attr
                elif any(_spawn_kind(c) for c in ast.walk(v)
                         if isinstance(c, ast.Call)):
                    spawned_locals.add(t.id)
        elif isinstance(node, ast.For) and isinstance(node.target,
                                                      ast.Name):
            loopvars.setdefault(node.target.id,
                                set()).update(_attr_names_in(node.iter))
        elif isinstance(node, ast.Await):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    awaited_names.add(sub.id)
        elif _is_wait_call(node):
            for arg in node.args + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        awaited_names.add(sub.id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "cancel" and not node.args:
            recv = node.func.value
            if isinstance(recv, ast.Name):
                cancelled.append((recv.id, None, node.lineno))
            elif isinstance(recv, ast.Attribute):
                cancelled.append((None, recv.attr, node.lineno))
    return alias, loopvars, spawned_locals, awaited_names, cancelled


@rule("cancel-without-await",
      ".cancel() on a spawned task that is never reaped")
def cancel_without_await(ctx: FileContext):
    """``task.cancel()`` only *requests* cancellation; until the task is
    awaited (or gathered) its finally blocks may still be running and
    its outcome is never observed.  Flagged when a task this file spawns
    is cancelled but never awaited anywhere in the file (attributes) or
    in the same function (locals)."""
    # pass 1 (file scope): which attributes hold spawned tasks, which
    # attributes are ever awaited/gathered
    spawned_attrs: set = set()
    awaited_attrs: set = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            if any(_spawn_kind(c) for c in ast.walk(node.value)
                   if isinstance(c, ast.Call)):
                for t in node.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Attribute):
                            spawned_attrs.add(sub.attr)
        if isinstance(node, ast.Call) and _spawn_kind(node):
            # spawn(coro(self._old_task)): the handle is passed into a
            # fresh coroutine — ownership transferred, it reaps it
            for arg in node.args:
                awaited_attrs.update(_attr_names_in(arg))
        if isinstance(node, ast.Await):
            awaited_attrs.update(_attr_names_in(node))
        elif _is_wait_call(node):
            for arg in node.args + [kw.value for kw in node.keywords]:
                awaited_attrs.update(_attr_names_in(arg))
        elif isinstance(node, ast.For):
            # reap loop:  for t in (self._a, self._b): await t
            if isinstance(node.target, ast.Name) and any(
                    isinstance(sub, ast.Await)
                    and node.target.id in {n.id for n in ast.walk(sub)
                                           if isinstance(n, ast.Name)}
                    for stmt in node.body for sub in walk_no_defs(stmt)):
                awaited_attrs.update(_attr_names_in(node.iter))

    # pass 2 (per scope): aliases, loop vars, locals, cancels
    for fn in _function_nodes(ctx.tree):
        alias, loopvars, spawned_locals, awaited_names, cancelled = \
            _local_scan(fn, ctx)
        for local, attr, lineno in cancelled:
            attrs: set = set()
            if attr is not None:
                attrs = {attr}
            elif local is not None:
                if local in alias:
                    attrs = {alias[local]}
                elif local in loopvars:
                    attrs = loopvars[local]
                elif local in spawned_locals:
                    if local not in awaited_names:
                        yield ctx.finding(
                            lineno, "cancel-without-await",
                            "task %r is cancelled but never awaited in "
                            "this function: await it (or gather it) so "
                            "its teardown completes and its outcome is "
                            "observed" % local)
                    continue
            hits = attrs & spawned_attrs
            for a in sorted(hits):
                if a not in awaited_attrs \
                        and (local is None or local not in awaited_names):
                    yield ctx.finding(
                        lineno, "cancel-without-await",
                        "task attribute %r is cancelled but never "
                        "awaited anywhere in this file: reap it "
                        "(await / gather(..., return_exceptions=True)) "
                        "after cancelling" % a)


# ------------------------------------------------------- lock-discipline

def _release_targets(stmts) -> set:
    out = set()
    for stmt in stmts:
        for node in walk_no_defs(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                recv = dotted(node.func.value)
                if recv:
                    out.add(recv)
    return out


def _enclosing_stmt(ctx: FileContext, node):
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = ctx.parents.get(cur)
    return cur


def _next_sibling(ctx: FileContext, stmt):
    parent = ctx.parents.get(stmt)
    if parent is None:
        return None
    for field in ("body", "orelse", "finalbody", "handlers"):
        seq = getattr(parent, field, None)
        if isinstance(seq, list) and stmt in seq:
            i = seq.index(stmt)
            return seq[i + 1] if i + 1 < len(seq) else None
    return None


@rule("lock-discipline", ".acquire() without async with / try-finally")
def lock_discipline(ctx: FileContext):
    """An explicit ``.acquire()`` whose release is not structurally
    guaranteed deadlocks the peer on the first exception between acquire
    and release.  Use ``async with lock:`` (or ``with lock:``); when
    staged acquisition is genuinely needed, the acquire must be the
    statement immediately before (or the first statement of) a ``try``
    whose ``finally`` releases the same lock."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"):
            continue
        target = dotted(node.func.value)
        if target is None:
            continue
        stmt = _enclosing_stmt(ctx, node)
        if stmt is None:
            continue
        # inside a try whose finally releases the target?
        protected = False
        cur = stmt
        while cur is not None:
            parent = ctx.parents.get(cur)
            if isinstance(parent, ast.Try) and cur in parent.body \
                    and target in _release_targets(parent.finalbody):
                protected = True
                break
            cur = parent
        if not protected:
            nxt = _next_sibling(ctx, stmt)
            if isinstance(nxt, ast.Try) \
                    and target in _release_targets(nxt.finalbody):
                protected = True
        if not protected:
            yield ctx.finding(
                node.lineno, "lock-discipline",
                "%s.acquire() without a structural release: use "
                "'async with %s:' or pair it with try/finally %s"
                ".release()" % (target, target, target))


# -------------------------------------------------------- unbounded-wait

_TIMEOUT_CTXS = {"timeout", "timeout_at"}


def _qualfunc(ctx: FileContext, node) -> str:
    owner = ctx.owners.get(node)
    return owner.name if owner is not None else "<module>"


@rule("unbounded-wait", "network primitive awaited without a timeout")
def unbounded_wait(ctx: FileContext):
    """A TCP connect (or a length-prefixed read) against a wedged peer
    — SIGSTOP, a blackholed route — hangs forever unless bounded.
    Awaits of the configured primitives must run under
    ``asyncio.wait_for`` or an enclosing ``asyncio.timeout`` block.
    Deliberately-unbounded call sites (idle read loops) go on the
    allowlist: config key ``unbounded-allow``, entries
    ``"<path-glob>::<function-glob>"``."""
    cfg = ctx.config
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        hit = None
        if name in cfg.unbounded_primitives:
            hit = name
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in cfg.unbounded_methods:
            hit = "." + node.func.attr
        if hit is None:
            continue
        if not isinstance(ctx.parents.get(node), ast.Await):
            # wrapped (wait_for(...) arg, ensure_future, ...) or a
            # handle stored for later: only the direct await is the
            # unbounded wait
            continue
        protected = False
        cur = node
        while cur is not None:
            parent = ctx.parents.get(cur)
            if isinstance(parent, ast.Call):
                pname = dotted(parent.func)
                if pname and pname.rsplit(".", 1)[-1] == "wait_for":
                    protected = True
                    break
            if isinstance(parent, (ast.AsyncWith, ast.With)):
                for item in parent.items:
                    cexpr = item.context_expr
                    if isinstance(cexpr, ast.Call):
                        cname = dotted(cexpr.func)
                        if cname and cname.rsplit(".", 1)[-1] \
                                in _TIMEOUT_CTXS:
                            protected = True
                            break
                if protected:
                    break
            if isinstance(parent, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                break
            cur = parent
        if protected:
            continue
        if allow_matches(cfg.unbounded_allow, ctx.path,
                         _qualfunc(ctx, node)):
            continue
        yield ctx.finding(
            node.lineno, "unbounded-wait",
            "await %s(...) with no timeout can hang on a wedged peer: "
            "wrap in asyncio.wait_for(...) or add the call site to the "
            "unbounded-allow list" % hit)


# ------------------------------------------------------ write-without-drain

# receiver-name convention for asyncio StreamWriters in this tree:
# `writer`, `*_writer`/`*writer`, and child-stdin pipes (`proc.stdin`)
_WRITERISH_LAST = ("writer", "stdin")


def _writerish(recv: str | None) -> bool:
    if not recv:
        return False
    last = recv.rsplit(".", 1)[-1]
    return last in _WRITERISH_LAST or last.endswith("writer")


def _innermost_loop(ctx: FileContext, node):
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        cur = ctx.parents.get(cur)
    return None


@rule("write-without-drain",
      "StreamWriter.write() in a loop with no await .drain()")
def write_without_drain(ctx: FileContext):
    """``writer.write()`` only queues bytes in the transport; without
    ``await writer.drain()`` in the same loop, a receiver slower than
    the producer grows the send buffer without bound — on the restore
    path that is the whole dataset resident in the sender's memory.
    Flagged: a write on a StreamWriter-named receiver (``writer``,
    ``*_writer``, ``proc.stdin``) inside a loop whose body never
    awaits ``.drain()`` on the SAME receiver.  A drain after the loop
    does not count: the buffer already peaked at the full batch."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "write"):
            continue
        recv = dotted(node.func.value)
        if not _writerish(recv):
            continue
        loop = _innermost_loop(ctx, node)
        if loop is None:
            continue
        drained = False
        for stmt in loop.body:
            for sub in walk_no_defs(stmt):
                if isinstance(sub, ast.Await) \
                        and isinstance(sub.value, ast.Call) \
                        and isinstance(sub.value.func, ast.Attribute) \
                        and sub.value.func.attr == "drain" \
                        and dotted(sub.value.func.value) == recv:
                    drained = True
                    break
            if drained:
                break
        if not drained:
            yield ctx.finding(
                node.lineno, "write-without-drain",
                "%s.write() in a loop without an 'await %s.drain()' in "
                "the same loop: a slow receiver grows the send buffer "
                "without bound — drain per iteration (or per bounded "
                "batch)" % (recv, recv))


# --------------------------------------------------------- span-not-closed

@rule("span-not-closed", "obs span() entered without with/async with")
def span_not_closed(ctx: FileContext):
    """``obs.span(...)`` is a context manager: calling it without
    entering it via ``with`` records nothing (the span never starts),
    and binding the generator for a manual ``__enter__`` leaks an
    open span — the ring never sees it and every child misparents.
    Callback-split lifecycles (the failover clock) must use the
    explicit ``SpanStore.start()``/``Span.end()`` API instead, which
    this rule deliberately ignores."""
    parents = ctx.parents
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None or name.rsplit(".", 1)[-1] != "span":
            continue
        # only the obs API: a bare `span` name, or a dotted path whose
        # receiver is the obs/spans module (`obs.span`, `spans.span`,
        # `manatee_tpu.obs.span`) — `tracer.span()` from some other
        # library is not ours to police
        if "." in name:
            recv = name.rsplit(".", 2)[-2]
            if recv not in ("obs", "spans"):
                continue
        if isinstance(parents.get(node), ast.withitem):
            continue
        yield ctx.finding(
            node.lineno, "span-not-closed",
            "span(...) must be entered with `with`/`async with`: a "
            "span that is never closed records nothing and misparents "
            "its children (use SpanStore.start()/Span.end() for "
            "callback-split lifecycles)")
