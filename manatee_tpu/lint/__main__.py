"""`python -m manatee_tpu.lint` — same CLI as tools/lint."""

import sys

from manatee_tpu.lint import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
