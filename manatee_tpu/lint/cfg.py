"""Per-function control-flow graphs for flow-sensitive lint rules.

:func:`build_cfg` turns one function def into a graph of basic blocks.
Each block carries an ordered *event* stream — await points, calls,
attribute/name loads and stores — plus the set of lock names held
throughout the block (every ``with``/``async with`` over a plain dotted
expression is treated as a lock scope; ``async with self._lock:`` is
the canonical form).  The flow-sensitive rules in
:mod:`manatee_tpu.lint.rules_flow` consume the graph through
:func:`scan_paths`, a forward reachability walk that tracks whether an
await point was crossed.

Deliberate approximations (documented so rule authors can reason about
false-negative surface):

- exception edges: every block created inside a ``try`` body gets an
  edge to each handler entry (an exception can arise anywhere in the
  body);
- ``finally`` bodies are wired on the normal path only; ``return``/
  ``raise`` shortcuts do not route through them (rules that care about
  finally-based cleanup inspect the AST lexically instead);
- nested ``def``/``lambda`` bodies are opaque: they execute in another
  context, so none of their events belong to this function's flow;
- generator expressions evaluate lazily but are treated as inline
  (their first iterable genuinely evaluates at the definition site);
- a ``yield`` inside an ``async def`` (async generator) counts as an
  await point: the consumer can interleave arbitrary work between
  items.  Sync-generator yields are not awaits.
"""

from __future__ import annotations

import ast
import dataclasses

# engine does not import cfg at module level (FileContext builds CFGs
# through a lazy import), so sharing its dotted() here is cycle-free
from manatee_tpu.lint.engine import dotted

# event kinds
AWAIT = "await"          # await expr / async for step / async with enter-exit
CALL = "call"            # any Call; name = dotted callee when resolvable
LOAD = "load"            # dotted attribute read (name = "self.x", "mod.Y")
STORE = "store"          # dotted attribute write
LOAD_NAME = "load_name"  # bare name read
STORE_NAME = "store_name"  # bare name write (assignment, for-target, ...)


@dataclasses.dataclass
class Event:
    kind: str
    node: ast.AST
    name: str | None = None

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)


class Block:
    __slots__ = ("bid", "events", "succs", "except_succs", "locks")

    def __init__(self, bid: int, locks: frozenset):
        self.bid = bid
        self.events: list[Event] = []
        self.succs: list[Block] = []
        # edges taken only when an exception unwinds out of this block
        # (try body -> handler entry).  Kept separate: cancellation
        # lands at await points on the NORMAL path, so rules about
        # cancel windows must not ride exception edges into handlers.
        self.except_succs: list[Block] = []
        self.locks = locks

    def __repr__(self):  # pragma: no cover - debugging aid
        return "Block(%d, %d events, ->%s, locks=%s)" % (
            self.bid, len(self.events),
            [s.bid for s in self.succs + self.except_succs],
            sorted(self.locks) or "")


class FuncCFG:
    """CFG of one function def; ``entry`` is always ``blocks[0]``."""

    def __init__(self, func):
        self.func = func
        self.blocks: list[Block] = []
        self.entry = self._new(frozenset())
        self._index: dict[int, tuple] | None = None

    def _new(self, locks: frozenset) -> Block:
        b = Block(len(self.blocks), locks)
        self.blocks.append(b)
        return b

    def events(self):
        """Yield (block, idx, event) over every block in creation order."""
        for b in self.blocks:
            for i, e in enumerate(b.events):
                yield b, i, e

    def position_of(self, node) -> tuple | None:
        """(block, idx) of the event anchored on *node* (by identity)."""
        if self._index is None:
            self._index = {}
            for b, i, e in self.events():
                self._index.setdefault(id(e.node), (b, i))
        return self._index.get(id(node))


_SCOPE_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class _Builder:
    def __init__(self, func):
        self.cfg = FuncCFG(func)
        self.cur = self.cfg.entry
        self.loops: list[tuple] = []     # (head block, exit block)
        self.is_async = isinstance(func, ast.AsyncFunctionDef)

    # -- plumbing --

    def _new(self, locks: frozenset | None = None) -> Block:
        return self.cfg._new(self.cur.locks if locks is None else locks)

    def _edge(self, a: Block, b: Block):
        if b not in a.succs:
            a.succs.append(b)

    def emit(self, kind: str, node, name: str | None = None):
        self.cur.events.append(Event(kind, node, name))

    def build(self) -> FuncCFG:
        self.seq(self.cfg.func.body)
        return self.cfg

    # -- statements --

    def seq(self, stmts):
        for s in stmts:
            self.stmt(s)

    def stmt(self, s):
        m = getattr(self, "stmt_" + type(s).__name__, None)
        if m is not None:
            m(s)
        else:
            self.generic_stmt(s)

    def generic_stmt(self, s):
        # Expr, Assert, Delete, Import, Global, Nonlocal, Pass, ...
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self.expr(child)

    def stmt_FunctionDef(self, s):
        # nested scope: opaque (its body runs in another context); its
        # own CFG is built separately by the rules
        for dec in s.decorator_list:
            self.expr(dec)

    stmt_AsyncFunctionDef = stmt_FunctionDef
    stmt_ClassDef = stmt_FunctionDef

    def stmt_Assign(self, s):
        self.expr(s.value)
        for t in s.targets:
            self.target(t)

    def stmt_AnnAssign(self, s):
        if s.value is not None:
            self.expr(s.value)
            self.target(s.target)

    def stmt_AugAssign(self, s):
        self.target_load(s.target)
        self.expr(s.value)
        self.target(s.target)

    def stmt_Return(self, s):
        self.expr(s.value)
        self.cur = self._new()       # unreachable continuation

    def stmt_Raise(self, s):
        self.expr(s.exc)
        self.expr(s.cause)
        self.cur = self._new()       # handlers are wired by stmt_Try

    def stmt_Break(self, s):
        if self.loops:
            self._edge(self.cur, self.loops[-1][1])
        self.cur = self._new()

    def stmt_Continue(self, s):
        if self.loops:
            self._edge(self.cur, self.loops[-1][0])
        self.cur = self._new()

    def stmt_If(self, s):
        self.expr(s.test)
        src = self.cur
        join = self._new()
        body = self._new()
        self._edge(src, body)
        self.cur = body
        self.seq(s.body)
        self._edge(self.cur, join)
        if s.orelse:
            els = self._new()
            self._edge(src, els)
            self.cur = els
            self.seq(s.orelse)
            self._edge(self.cur, join)
        else:
            self._edge(src, join)
        self.cur = join

    def stmt_While(self, s):
        head = self._new()
        self._edge(self.cur, head)
        self.cur = head
        self.expr(s.test)
        exit_ = self._new()
        body = self._new()
        self._edge(head, body)
        self.loops.append((head, exit_))
        self.cur = body
        self.seq(s.body)
        self._edge(self.cur, head)
        self.loops.pop()
        if s.orelse:
            els = self._new()
            self._edge(head, els)
            self.cur = els
            self.seq(s.orelse)
            self._edge(self.cur, exit_)
        else:
            self._edge(head, exit_)
        self.cur = exit_

    def stmt_For(self, s):
        self.expr(s.iter)
        head = self._new()
        self._edge(self.cur, head)
        self.cur = head
        if isinstance(s, ast.AsyncFor):
            self.emit(AWAIT, s)      # each __anext__ is an await point
        self.target(s.target)
        exit_ = self._new()
        body = self._new()
        self._edge(head, body)
        self.loops.append((head, exit_))
        self.cur = body
        self.seq(s.body)
        self._edge(self.cur, head)
        self.loops.pop()
        if s.orelse:
            els = self._new()
            self._edge(head, els)
            self.cur = els
            self.seq(s.orelse)
            self._edge(self.cur, exit_)
        else:
            self._edge(head, exit_)
        self.cur = exit_

    stmt_AsyncFor = stmt_For

    def stmt_With(self, s):
        entry_locks = self.cur.locks
        locknames = set()
        for item in s.items:
            self.expr(item.context_expr)
            d = dotted(item.context_expr)
            if d:
                locknames.add(d)
            if isinstance(s, ast.AsyncWith):
                self.emit(AWAIT, s)  # __aenter__
            if item.optional_vars is not None:
                self.target(item.optional_vars)
        body = self._new(entry_locks | frozenset(locknames))
        self._edge(self.cur, body)
        self.cur = body
        self.seq(s.body)
        after = self._new(entry_locks)
        self._edge(self.cur, after)
        self.cur = after
        if isinstance(s, ast.AsyncWith):
            # __aexit__ awaits; a lock is released by then, so the
            # event lands in the after-block (outside the held scope)
            self.emit(AWAIT, s)

    stmt_AsyncWith = stmt_With

    def stmt_Try(self, s):
        body_start = len(self.cfg.blocks)
        body_first = self._new()
        self._edge(self.cur, body_first)
        self.cur = body_first
        self.seq(s.body)
        # snapshot BEFORE the orelse, and give the orelse its own
        # block: an exception in the else clause is NOT caught by this
        # try's handlers, so else code must not grow exception edges
        body_blocks = self.cfg.blocks[body_start:]
        if s.orelse:
            els = self._new()
            self._edge(self.cur, els)
            self.cur = els
            self.seq(s.orelse)
        body_end = self.cur
        handler_exits = [body_end]
        handler_entries = []
        for h in s.handlers:
            he = self._new()
            handler_entries.append(he)
            self.cur = he
            if h.name:
                self.emit(STORE_NAME, h, h.name)
            self.seq(h.body)
            handler_exits.append(self.cur)
        # an exception can arise anywhere in the body: every body block
        # reaches every handler entry (via exception edges)
        for b in body_blocks:
            for he in handler_entries:
                if he not in b.except_succs:
                    b.except_succs.append(he)
        fin = self._new()
        for x in handler_exits:
            self._edge(x, fin)
        self.cur = fin
        if s.finalbody:
            self.seq(s.finalbody)

    if hasattr(ast, "TryStar"):      # pragma: no branch
        stmt_TryStar = stmt_Try

    def stmt_Match(self, s):
        self.expr(s.subject)
        src = self.cur
        join = self._new()
        for case in s.cases:
            cb = self._new()
            self._edge(src, cb)
            self.cur = cb
            if case.guard is not None:
                self.expr(case.guard)
            self.seq(case.body)
            self._edge(self.cur, join)
        self._edge(src, join)        # no case matched
        self.cur = join

    # -- assignment targets --

    def target(self, t):
        if isinstance(t, ast.Name):
            self.emit(STORE_NAME, t, t.id)
        elif isinstance(t, ast.Attribute):
            self.expr(t.value)       # receiver loads (`self.a` in self.a.b=)
            d = dotted(t)
            if d:
                self.emit(STORE, t, d)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.target(e)
        elif isinstance(t, ast.Starred):
            self.target(t.value)
        elif isinstance(t, ast.Subscript):
            self.expr(t.value)
            self.expr(t.slice)

    def target_load(self, t):
        """The read half of an AugAssign target."""
        if isinstance(t, ast.Name):
            self.emit(LOAD_NAME, t, t.id)
        elif isinstance(t, ast.Attribute):
            self.expr(t.value)
            d = dotted(t)
            if d:
                self.emit(LOAD, t, d)
        elif isinstance(t, ast.Subscript):
            self.expr(t.value)
            self.expr(t.slice)

    # -- expressions (events in evaluation order) --

    def expr(self, e):
        if e is None:
            return
        m = getattr(self, "expr_" + type(e).__name__, None)
        if m is not None:
            m(e)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.expr(child)

    def expr_Await(self, e):
        self.expr(e.value)
        self.emit(AWAIT, e)          # the operand is computed, THEN awaited

    def expr_Call(self, e):
        self.expr(e.func)
        for a in e.args:
            self.expr(a)
        for kw in e.keywords:
            self.expr(kw.value)
        self.emit(CALL, e, dotted(e.func))

    def expr_Attribute(self, e):
        d = dotted(e)
        if d is not None:
            self.emit(LOAD, e, d)
        else:
            # receiver is a call/subscript/...: recurse into it
            self.expr(e.value)

    def expr_Name(self, e):
        self.emit(LOAD_NAME, e, e.id)

    def expr_Lambda(self, e):
        for d in e.args.defaults + [d for d in e.args.kw_defaults
                                    if d is not None]:
            self.expr(d)             # defaults evaluate here; body is opaque

    def expr_NamedExpr(self, e):
        self.expr(e.value)
        self.emit(STORE_NAME, e.target, e.target.id)

    def expr_Yield(self, e):
        self.expr(e.value)
        if self.is_async:
            self.emit(AWAIT, e)      # async generator: consumer interleaves

    def expr_YieldFrom(self, e):
        self.expr(e.value)

    def _comp(self, e):
        for gen in e.generators:
            self.expr(gen.iter)
            if gen.is_async:
                self.emit(AWAIT, e)
            for cond in gen.ifs:
                self.expr(cond)
        if isinstance(e, ast.DictComp):
            self.expr(e.key)
            self.expr(e.value)
        else:
            self.expr(e.elt)

    expr_ListComp = _comp
    expr_SetComp = _comp
    expr_DictComp = _comp
    expr_GeneratorExp = _comp


def build_cfg(func) -> FuncCFG:
    """CFG for one ``FunctionDef``/``AsyncFunctionDef``."""
    return _Builder(func).build()


def iter_function_defs(tree):
    """Every function def in *tree*, including nested ones (each gets
    its own CFG; a nested def's events never leak into its parent's)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- path queries --

KEEP = None
STOP = "stop"
HIT = "hit"


def scan_paths(cfg: FuncCFG, start: tuple, classify,
               follow_exceptions: bool = True,
               suspends=None) -> list:
    """Forward reachability from *start* = (block, idx), exclusive.

    ``classify(event, awaited)`` is called for every event reachable
    strictly after the start position; ``awaited`` is True when some
    await point lies on the path taken so far.  It returns:

    - ``KEEP`` (None): continue through this event;
    - ``STOP``: this path is resolved (e.g. the handle was protected);
    - ``HIT``: record ``(event, awaited)`` and stop this path.

    Await events flip ``awaited`` for everything downstream of them.
    Each (position, awaited) state is visited once, so loops terminate;
    returns the list of hits.  With ``follow_exceptions=False`` the
    walk sticks to normal-flow edges (cancellation-window rules: a
    cancel lands at an await on the normal path, never "inside" an
    exception edge).

    ``suspends(event) -> bool``, when given, filters AWAIT events: only
    those it accepts flip ``awaited``.  The v4 rules pass a summary-
    backed filter so ``await helper()`` of a project coroutine proven
    never to suspend is NOT an interleave point (the event loop runs it
    inline); without the callable every await suspends, the sound v3
    default.
    """
    hits = []
    hit_keys = set()
    seen = set()
    b, i = start
    stack = [(b, i + 1, False)]
    while stack:
        blk, idx, awaited = stack.pop()
        if idx >= len(blk.events):
            succs = blk.succs + (blk.except_succs if follow_exceptions
                                 else [])
            for succ in succs:
                key = ("b", succ.bid, awaited)
                if key not in seen:
                    seen.add(key)
                    stack.append((succ, 0, awaited))
            continue
        key = (blk.bid, idx, awaited)
        if key in seen:
            continue
        seen.add(key)
        e = blk.events[idx]
        verdict = classify(e, awaited)
        if verdict == STOP:
            continue
        if verdict == HIT:
            hkey = (id(e.node), awaited)
            if hkey not in hit_keys:
                hit_keys.add(hkey)
                hits.append((e, awaited))
            continue
        if e.kind == AWAIT and (suspends is None or suspends(e)):
            awaited = True
        stack.append((blk, idx + 1, awaited))
    return hits
