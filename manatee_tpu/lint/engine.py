"""mnt-lint engine: rule registry, per-line suppressions, output.

A *rule* is a generator function ``fn(ctx) -> Iterator[Finding]``
registered under a kebab-case name with the :func:`rule` decorator.
Each file is parsed once into a :class:`FileContext` (source text, AST,
lazily-built parent/owner maps) and every enabled rule runs over it.

Suppressions are per line::

    risky_line()   # mnt-lint: disable=<rule>
    other()        # mnt-lint: disable=<rule>,<rule2>
    anything()     # mnt-lint: disable=<all>

A suppression matches findings whose reported line is the line the
comment sits on (for multi-line statements that is the first line).
Suppressed findings are kept separately in :class:`LintResult` so the
JSON output — and the test suite — can account for them.

Configuration comes from defaults < a JSON config file
(``--config``, or ``.mnt-lint.json`` in the working directory when
present) < CLI flags.  See docs/lint.md for the keys.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import fnmatch
import json
import re
import sys
from pathlib import Path
from typing import Callable, Iterator

DEFAULT_PATHS = ["manatee_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]
# directory-walk exclusions (explicit file arguments are always linted:
# the fixture suite under tests/data/lint depends on that)
DEFAULT_EXCLUDE = ["tests/data"]

_SUPPRESS_RE = re.compile(r"#\s*mnt-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def render(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.rule,
                                   self.msg)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Config:
    max_line: int = 100
    disable: frozenset = frozenset()
    exclude: tuple = tuple(DEFAULT_EXCLUDE)
    # unbounded-wait: dotted call names / method names whose direct
    # await must be bounded by wait_for or an enclosing timeout block
    unbounded_primitives: frozenset = frozenset(
        {"asyncio.open_connection"})
    unbounded_methods: frozenset = frozenset({"readexactly", "readuntil"})
    # "<path-glob>::<function-glob>" entries where an unbounded await is
    # deliberate (e.g. an idle read loop)
    unbounded_allow: frozenset = frozenset()
    # extra dotted call names for blocking-call-in-async
    blocking_extra: frozenset = frozenset()
    # per-path rule scoping: (("<path-glob>", frozenset({rule, ...})),
    # ...) — those rules are off for matching files.  This is how the
    # repo keeps the strict profile on production packages while test/
    # bench code drops e.g. the sync-file-I/O rule (tiny fixture writes
    # in a test do not need a worker thread).
    path_disable: tuple = ()

    _KEYS = {
        "max-line": "max_line",
        "disable": "disable",
        "exclude": "exclude",
        "unbounded-primitives": "unbounded_primitives",
        "unbounded-methods": "unbounded_methods",
        "unbounded-allow": "unbounded_allow",
        "blocking-extra": "blocking_extra",
        "path-disable": "path_disable",
    }

    @classmethod
    def from_dict(cls, data: dict, base: "Config | None" = None
                  ) -> "Config":
        cfg = base or cls()
        kw = {}
        for key, val in data.items():
            field = cls._KEYS.get(key)
            if field is None:
                raise ValueError("unknown mnt-lint config key: %r" % key)
            if field == "max_line":
                kw[field] = int(val)
            elif field == "exclude":
                kw[field] = tuple(val)
            elif field == "path_disable":
                kw[field] = tuple(sorted(
                    (glob, frozenset(rules))
                    for glob, rules in dict(val).items()))
            else:
                kw[field] = frozenset(val)
        return dataclasses.replace(cfg, **kw)

    def disabled_for(self, path: str) -> frozenset:
        """Rules off for *path*: the global disable set plus any
        path-disable entries whose glob matches."""
        out = set(self.disable)
        for glob, rules in self.path_disable:
            if fnmatch.fnmatch(path, glob) \
                    or fnmatch.fnmatch(path, "*/" + glob):
                out.update(rules)
        return frozenset(out)

    @classmethod
    def from_file(cls, path: str | Path,
                  base: "Config | None" = None) -> "Config":
        with open(path) as fh:
            data = json.load(fh)
        if not isinstance(data, dict):
            raise ValueError("%s: config must be a JSON object" % path)
        return cls.from_dict(data, base)


@dataclasses.dataclass
class LintResult:
    path: str
    findings: list
    suppressed: list


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    fn: Callable


RULES: dict[str, Rule] = {}


def rule(name: str, summary: str):
    """Register a rule function under *name* (kebab-case)."""
    def deco(fn):
        if name in RULES:
            raise ValueError("duplicate rule %r" % name)
        RULES[name] = Rule(name, summary, fn)
        return fn
    return deco


# 'syntax' is engine-level (a file that does not parse runs no rules)
# but registered so --list-rules and the disable machinery see it
@rule("syntax", "file must parse (ast.parse)")
def _syntax_rule(ctx):
    return iter(())


# ---- AST helpers shared by rules ----

def dotted(node) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def walk_no_defs(node) -> Iterator[ast.AST]:
    """Walk *node*'s subtree without descending into nested function
    definitions or lambdas (their bodies run in a different execution
    context, so e.g. an ``await`` there is not an await *here*)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)


def has_await(stmts) -> bool:
    """True when the statement list contains an await point (await /
    async for / async with) in the current execution context."""
    for stmt in stmts:
        for node in walk_no_defs(stmt):
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
    return False


class FileContext:
    def __init__(self, path: str, text: str, tree: ast.AST,
                 config: Config):
        self.path = path
        self.text = text
        self.tree = tree
        self.config = config
        self.lines = text.splitlines()
        self._parents: dict | None = None
        self._owners: dict | None = None

    def finding(self, line: int, rule_name: str, msg: str) -> Finding:
        return Finding(self.path, line, rule_name, msg)

    @property
    def parents(self) -> dict:
        """node -> immediate parent node."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    @property
    def owners(self) -> dict:
        """node -> nearest enclosing function def (or None at module
        scope).  Lambdas count as a scope boundary but are never
        reported as the owner."""
        if self._owners is None:
            owners: dict = {}

            def rec(node, owner):
                for child in ast.iter_child_nodes(node):
                    owners[child] = owner
                    rec(child,
                        child if isinstance(child, _SCOPE_NODES) else owner)

            rec(self.tree, None)
            self._owners = owners
        return self._owners

    def async_owner(self, node):
        """The enclosing async def of *node*, or None (lambda and sync
        def boundaries block ownership)."""
        owner = self.owners.get(node)
        return owner if isinstance(owner, ast.AsyncFunctionDef) else None


# ---- suppression handling ----

def parse_suppressions(text: str) -> dict:
    """line number -> set of rule names (or {'all'})."""
    out: dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            if names:
                out[i] = names
    return out


# ---- core per-file run ----

def check_source(text: str, path: str = "<string>",
                 config: Config | None = None) -> LintResult:
    config = config or Config()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        f = Finding(path, e.lineno or 0, "syntax",
                    "syntax error: %s" % e.msg)
        return LintResult(path, [f], [])
    except ValueError as e:        # e.g. source with null bytes
        return LintResult(path, [Finding(path, 0, "syntax", str(e))], [])
    ctx = FileContext(path, text, tree, config)
    disabled = config.disabled_for(path)
    findings: list[Finding] = []
    for r in RULES.values():
        if r.name in disabled:
            continue
        findings.extend(r.fn(ctx))
    supp = parse_suppressions(text)
    kept, suppressed = [], []
    for f in sorted(findings):
        names = supp.get(f.line, ())
        if "all" in names or f.rule in names:
            suppressed.append(f)
        else:
            kept.append(f)
    return LintResult(path, kept, suppressed)


def check_file(path: Path, config: Config | None = None) -> LintResult:
    try:
        text = path.read_text()
    except UnicodeDecodeError:
        return LintResult(str(path),
                          [Finding(str(path), 0, "syntax", "not utf-8")],
                          [])
    except OSError as e:
        return LintResult(str(path),
                          [Finding(str(path), 0, "syntax",
                                   "unreadable: %s" % e)], [])
    return check_source(text, str(path), config)


# ---- file iteration ----

def _is_python_script(p: Path) -> bool:
    try:
        head = p.open("rb").readline()
    except OSError:
        return False
    return head.startswith(b"#!") and b"python" in head


def _excluded(p: Path, config: Config) -> bool:
    s = str(p)
    return any(part in s for part in config.exclude)


def iter_files(paths, config: Config) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            found = sorted(p.rglob("*.py"))
            # shebang scripts without .py (tools/lint itself, tools/
            # mkdevcluster, tests/fakepg/postgres, ...) are gated too
            found += sorted(
                f for f in p.rglob("*")
                if f.is_file() and f.suffix == "" and _is_python_script(f))
            for f in found:
                if not _excluded(f, config):
                    yield f
        elif p.is_file() and (p.suffix == ".py" or _is_python_script(p)):
            # explicit file arguments bypass the exclude list
            yield p


def check_paths(paths, config: Config | None = None
                ) -> tuple[int, list, list]:
    """(files checked, findings, suppressed findings) over *paths*."""
    config = config or Config()
    n = 0
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    for f in iter_files(paths, config):
        n += 1
        res = check_file(f, config)
        findings.extend(res.findings)
        suppressed.extend(res.suppressed)
    return n, findings, suppressed


# ---- allowlist matching (used by unbounded-wait) ----

def allow_matches(entries, path: str, funcname: str) -> bool:
    """True when any "<path-glob>::<func-glob>" entry matches.  The path
    part matches against the end of the reported path so entries stay
    stable regardless of how the tool was invoked."""
    for entry in entries:
        pat_path, sep, pat_fn = entry.partition("::")
        if not sep:
            pat_path, pat_fn = entry, "*"
        if not fnmatch.fnmatch(funcname or "", pat_fn):
            continue
        if fnmatch.fnmatch(path, pat_path) \
                or fnmatch.fnmatch(path, "*" + pat_path.lstrip("*")):
            return True
    return False


# ---- CLI ----

def _build_config(args) -> Config:
    cfg = Config()
    cfg_path = args.config
    if cfg_path is None and Path(".mnt-lint.json").is_file():
        cfg_path = ".mnt-lint.json"
    if cfg_path:
        cfg = Config.from_file(cfg_path, cfg)
    overrides = {}
    if args.max_line is not None:
        overrides["max_line"] = args.max_line
    if args.disable:
        names = set(cfg.disable)
        for chunk in args.disable:
            names.update(n.strip() for n in chunk.split(",") if n.strip())
        unknown = names - set(RULES)
        if unknown:
            raise SystemExit("mnt-lint: unknown rule(s): %s"
                             % ", ".join(sorted(unknown)))
        overrides["disable"] = frozenset(names)
    if args.unbounded_allow:
        overrides["unbounded_allow"] = (cfg.unbounded_allow
                                        | frozenset(args.unbounded_allow))
    return dataclasses.replace(cfg, **overrides)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mnt-lint",
        description="stdlib static checks incl. async-concurrency rules "
                    "(docs/lint.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to check (default: the repo tree)")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE[,RULE...]",
                    help="disable rules by name")
    ap.add_argument("--config", metavar="FILE",
                    help="JSON config (default: ./.mnt-lint.json if "
                         "present)")
    ap.add_argument("--max-line", type=int, default=None)
    ap.add_argument("--unbounded-allow", action="append", default=[],
                    metavar="PATH::FUNC",
                    help="allowlist entry for the unbounded-wait rule")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name in sorted(RULES):
            print("%-*s  %s" % (width, name, RULES[name].summary))
        return 0

    config = _build_config(args)
    n, findings, suppressed = check_paths(args.paths or DEFAULT_PATHS,
                                          config)
    if args.format == "json":
        print(json.dumps({
            "files": n,
            "problems": len(findings),
            "findings": [f.as_dict() for f in findings],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        print("mnt-lint: %d files, %d problems (%d suppressed)"
              % (n, len(findings), len(suppressed)), file=sys.stderr)
    return 1 if findings else 0
