"""mnt-lint engine: rule registry, per-line suppressions, output.

A *rule* is a generator function ``fn(ctx) -> Iterator[Finding]``
registered under a kebab-case name with the :func:`rule` decorator.
Each file is parsed once into a :class:`FileContext` (source text, AST,
lazily-built parent/owner maps) and every enabled rule runs over it.

Suppressions are per line::

    risky_line()   # mnt-lint: disable=<rule>
    other()        # mnt-lint: disable=<rule>,<rule2>
    anything()     # mnt-lint: disable=<all>

A suppression matches findings whose reported line is the line the
comment sits on (for multi-line statements that is the first line).
Suppressed findings are kept separately in :class:`LintResult` so the
JSON output — and the test suite — can account for them.  A disable
that suppresses nothing is itself reported (``unused-suppression``):
stale suppressions are debt that must not outlive the finding.

Checked *annotations* ride the same comment namespace: a
``# mnt-lint: atomic-section`` marker line (optionally ``=<label>``)
opens a region that a matching end marker (the same comment prefix
followed by ``end-atomic-section``) closes.  Both markers must end the
line — the ``$``-anchored regexes below keep prose mentions (like this
docstring) from registering.  The region is an assertion the
``atomic-section-broken`` rule verifies (an await inside it is a
finding); the engine accounts for the markers themselves — unmatched
or dead regions are reported like unused disables.

Configuration comes from defaults < a JSON config file
(``--config``, or ``.mnt-lint.json`` in the working directory when
present) < CLI flags.  See docs/lint.md for the keys.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import fnmatch
import hashlib
import json
import re
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Iterator

DEFAULT_PATHS = ["manatee_tpu", "tests", "tools", "bench.py",
                 "__graft_entry__.py"]
# directory-walk exclusions (explicit file arguments are always linted:
# the fixture suite under tests/data/lint depends on that)
DEFAULT_EXCLUDE = ["tests/data"]

DEFAULT_CACHE = ".mnt-lint-cache.json"

_SUPPRESS_RE = re.compile(r"#\s*mnt-lint:\s*disable=([A-Za-z0-9_,\- ]+)")
_ATOMIC_BEGIN_RE = re.compile(
    r"#\s*mnt-lint:\s*atomic-section(?:=([A-Za-z0-9_.\-]+))?\s*$")
_ATOMIC_END_RE = re.compile(r"#\s*mnt-lint:\s*end-atomic-section\s*$")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    rule: str
    msg: str

    def render(self) -> str:
        return "%s:%d: [%s] %s" % (self.path, self.line, self.rule,
                                   self.msg)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Config:
    max_line: int = 100
    disable: frozenset = frozenset()
    exclude: tuple = tuple(DEFAULT_EXCLUDE)
    # unbounded-wait: dotted call names / method names whose direct
    # await must be bounded by wait_for or an enclosing timeout block
    unbounded_primitives: frozenset = frozenset(
        {"asyncio.open_connection"})
    unbounded_methods: frozenset = frozenset({"readexactly", "readuntil"})
    # "<path-glob>::<function-glob>" entries where an unbounded await is
    # deliberate (e.g. an idle read loop)
    unbounded_allow: frozenset = frozenset()
    # extra dotted call names for blocking-call-in-async
    blocking_extra: frozenset = frozenset()
    # per-path rule scoping: (("<path-glob>", frozenset({rule, ...})),
    # ...) — those rules are off for matching files.  This is how the
    # repo keeps the strict profile on production packages while test/
    # bench code drops e.g. the sync-file-I/O rule (tiny fixture writes
    # in a test do not need a worker thread).
    path_disable: tuple = ()
    # atomic-section-broken: method-name globs for the load/save halves
    # of a load-modify-save pair routed through calls (dirstore's
    # `_load_meta`/`_save_meta`).  The glob's literal core is stripped
    # to pair them ("_load_meta" <-> "_save_meta" share the "_·_meta"
    # stem).
    atomic_load_calls: frozenset = frozenset({"*load*"})
    atomic_save_calls: frozenset = frozenset({"*save*"})
    # cancel-unsafe-acquire: handle-yielding acquires — the bound
    # result is the resource.  An entry with a dot matches the dotted
    # callee exactly; a bare entry matches the last component (so
    # "open" covers the builtin and `path.open`).
    acquire_calls: frozenset = frozenset({
        "open", "os.fdopen", "socket.socket",
        "open_connection", "open_unix_connection",
        "start_server", "start_unix_server",
        "create_server", "create_unix_server",
        "create_subprocess_exec", "create_subprocess_shell",
    })
    # side-effect acquires: the resource exists but no handle comes
    # back (dataset `create` — the cancel window that stranded
    # meta-less debris in PR 8), checked in discarded form: execution
    # must enter a cleanup-capable try before the next await.  A
    # znode-style create whose bound result is just a PATH string is
    # deliberately not in acquire_calls.
    acquire_discard_calls: frozenset = frozenset({"create"})
    # "<path-glob>::<function-glob>" entries where an unguarded
    # side-effect acquire is deliberate — test/bench setup whose
    # cleanup is directory teardown rather than a try block
    acquire_discard_allow: frozenset = frozenset()
    # lockset-inconsistent: how many lock-guarded access sites establish
    # an attribute's lock discipline (below this, a lock seen once is
    # just coincidence, not a contract)
    lockset_min_guarded: int = 2
    # transitive-blocking-in-async: "<path-glob>::<qualname-glob>"
    # entries naming helpers whose blocking is a DOCUMENTED design
    # decision (dirstore's no-await meta RMW, coordd's synchronous
    # shutdown snapshot).  The helper's may_block summary is UNCHANGED
    # — the runtime stall watchdog still derives it, keeping the
    # two-sided obs.loop.stall contract honest — but chains ending
    # only in declared helpers are not reported at call sites.
    # Unused entries are flagged by unused-suppression on full runs.
    blocking_by_design: frozenset = frozenset()
    # v4: consult interprocedural summaries (callgraph.py/summaries.py)
    # at call events.  Off = exact v3 per-function behavior; the seeded
    # -bug regression tests pin both sides of that contract.
    interproc: bool = True

    _KEYS = {
        "max-line": "max_line",
        "disable": "disable",
        "exclude": "exclude",
        "unbounded-primitives": "unbounded_primitives",
        "unbounded-methods": "unbounded_methods",
        "unbounded-allow": "unbounded_allow",
        "blocking-extra": "blocking_extra",
        "path-disable": "path_disable",
        "atomic-load-calls": "atomic_load_calls",
        "atomic-save-calls": "atomic_save_calls",
        "acquire-calls": "acquire_calls",
        "acquire-discard-calls": "acquire_discard_calls",
        "acquire-discard-allow": "acquire_discard_allow",
        "blocking-by-design": "blocking_by_design",
        "lockset-min-guarded": "lockset_min_guarded",
        "interprocedural": "interproc",
        "notes": None,       # free-form justifications, ignored here
    }

    @classmethod
    def from_dict(cls, data: dict, base: "Config | None" = None
                  ) -> "Config":
        cfg = base or cls()
        kw = {}
        for key, val in data.items():
            if key not in cls._KEYS:
                raise ValueError("unknown mnt-lint config key: %r" % key)
            field = cls._KEYS[key]
            if field is None:
                continue
            if field in ("max_line", "lockset_min_guarded"):
                kw[field] = int(val)
            elif field == "interproc":
                kw[field] = bool(val)
            elif field == "exclude":
                kw[field] = tuple(val)
            elif field == "path_disable":
                kw[field] = tuple(sorted(
                    (glob, frozenset(rules))
                    for glob, rules in dict(val).items()))
            else:
                kw[field] = frozenset(val)
        return dataclasses.replace(cfg, **kw)

    def disabled_for(self, path: str) -> frozenset:
        """Rules off for *path*: the global disable set plus any
        path-disable entries whose glob matches."""
        out = set(self.disable)
        for glob, rules in self.path_disable:
            if fnmatch.fnmatch(path, glob) \
                    or fnmatch.fnmatch(path, "*/" + glob):
                out.update(rules)
        return frozenset(out)

    @classmethod
    def from_file(cls, path: str | Path,
                  base: "Config | None" = None) -> "Config":
        with open(path) as fh:
            data = json.load(fh)
        if not isinstance(data, dict):
            raise ValueError("%s: config must be a JSON object" % path)
        return cls.from_dict(data, base)


@dataclasses.dataclass
class LintResult:
    path: str
    findings: list
    suppressed: list
    # "<path-glob>::<func-glob>" allowlist entries a rule consulted and
    # matched while checking this file (cached with the result so a
    # full cached run can still report unused allowlist entries)
    allow_used: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    fn: Callable


RULES: dict[str, Rule] = {}


def rule(name: str, summary: str):
    """Register a rule function under *name* (kebab-case)."""
    def deco(fn):
        if name in RULES:
            raise ValueError("duplicate rule %r" % name)
        RULES[name] = Rule(name, summary, fn)
        return fn
    return deco


# 'syntax' is engine-level (a file that does not parse runs no rules)
# but registered so --list-rules and the disable machinery see it
@rule("syntax", "file must parse (ast.parse)")
def _syntax_rule(ctx):
    return iter(())


# engine-level too: computed in check_source after suppression matching
# (a rule generator cannot see which suppressions ended up unused)
@rule("unused-suppression",
      "disable comment or annotation that suppresses/verifies nothing")
def _unused_suppression_rule(ctx):
    return iter(())


# ---- AST helpers shared by rules ----

def dotted(node) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def walk_no_defs(node) -> Iterator[ast.AST]:
    """Walk *node*'s subtree without descending into nested function
    definitions or lambdas (their bodies run in a different execution
    context, so e.g. an ``await`` there is not an await *here*)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)


def has_await(stmts) -> bool:
    """True when the statement list contains an await point (await /
    async for / async with) in the current execution context."""
    for stmt in stmts:
        for node in walk_no_defs(stmt):
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
    return False


class FileContext:
    def __init__(self, path: str, text: str, tree: ast.AST,
                 config: Config):
        self.path = path
        self.text = text
        self.tree = tree
        self.config = config
        self.lines = text.splitlines()
        self._parents: dict | None = None
        self._owners: dict | None = None
        self._cfgs: dict | None = None
        self._annotations: list | None = None
        self._module_globals: frozenset | None = None
        self._summaries = None
        self._summaries_set = False

    def finding(self, line: int, rule_name: str, msg: str) -> Finding:
        return Finding(self.path, line, rule_name, msg)

    @property
    def parents(self) -> dict:
        """node -> immediate parent node."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    @property
    def owners(self) -> dict:
        """node -> nearest enclosing function def (or None at module
        scope).  Lambdas count as a scope boundary but are never
        reported as the owner."""
        if self._owners is None:
            owners: dict = {}

            def rec(node, owner):
                for child in ast.iter_child_nodes(node):
                    owners[child] = owner
                    rec(child,
                        child if isinstance(child, _SCOPE_NODES) else owner)

            rec(self.tree, None)
            self._owners = owners
        return self._owners

    def async_owner(self, node):
        """The enclosing async def of *node*, or None (lambda and sync
        def boundaries block ownership)."""
        owner = self.owners.get(node)
        return owner if isinstance(owner, ast.AsyncFunctionDef) else None

    @property
    def cfgs(self) -> dict:
        """function def node -> FuncCFG, for every def in the file
        (built once, shared by all flow-sensitive rules)."""
        if self._cfgs is None:
            from manatee_tpu.lint import cfg as cfgmod
            self._cfgs = {fn: cfgmod.build_cfg(fn)
                          for fn in cfgmod.iter_function_defs(self.tree)}
        return self._cfgs

    @property
    def summaries(self):
        """The interprocedural :class:`~.summaries.SummaryDB` rules
        consult at call events, or None with ``interproc`` off.

        ``check_paths`` injects the project-wide database; a bare
        ``check_source`` (unit fixtures, editor integration) lazily
        builds a single-file one, so in-file helper chains still
        resolve even without the full tree."""
        if not self.config.interproc:
            return None
        if not self._summaries_set:
            from manatee_tpu.lint import summaries as summod
            self._summaries = summod.SummaryDB.build_from_sources(
                [(self.path, self.text, self.tree)], self.config)
            self._summaries_set = True
        return self._summaries

    @summaries.setter
    def summaries(self, db):
        self._summaries = db
        self._summaries_set = True

    @property
    def annotations(self) -> list:
        """Well-formed atomic-section regions: [(begin, end, label)].
        Malformed markers are accounted for by the engine itself."""
        if self._annotations is None:
            self._annotations, _ = parse_annotations(self.text)
        return self._annotations

    @property
    def module_globals(self) -> frozenset:
        """Names bound by module-level statements (assignment targets;
        imports and defs are not *mutable* state and stay out)."""
        if self._module_globals is None:
            names: set[str] = set()
            for node in self.tree.body:
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = node.targets \
                        if isinstance(node, ast.Assign) else [node.target]
                    for t in targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                names.add(sub.id)
            self._module_globals = frozenset(names)
        return self._module_globals


# ---- suppression handling ----

def parse_suppressions(text: str) -> dict:
    """line number -> set of rule names (or {'all'})."""
    out: dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            if names:
                out[i] = names
    return out


def parse_annotations(text: str) -> tuple[list, list]:
    """Atomic-section markers -> (regions, problems).

    ``regions`` is ``[(begin_line, end_line, label)]`` for matched
    begin/end pairs; ``problems`` is ``[(line, msg)]`` for unmatched or
    nested markers.  Regions do not nest (an atomic claim inside an
    atomic claim adds nothing and usually means a stray marker).
    """
    regions, problems = [], []
    open_at: tuple | None = None
    for i, line in enumerate(text.splitlines(), 1):
        if _ATOMIC_END_RE.search(line):
            if open_at is None:
                problems.append(
                    (i, "end-atomic-section without a matching "
                        "atomic-section begin"))
            else:
                regions.append((open_at[0], i, open_at[1]))
                open_at = None
            continue
        m = _ATOMIC_BEGIN_RE.search(line)
        if m:
            if open_at is not None:
                problems.append(
                    (i, "atomic-section opened at line %d is still "
                        "open (sections do not nest)" % open_at[0]))
            else:
                open_at = (i, m.group(1))
    if open_at is not None:
        problems.append(
            (open_at[0], "atomic-section is never closed (add a "
                         "'# mnt-lint: end-atomic-section' marker)"))
    return regions, problems


def _annotation_accounting(ctx: FileContext) -> Iterator[Finding]:
    """Unmatched markers, plus regions that cannot verify anything: a
    section outside any async execution context has no await points to
    forbid, so the claim is dead weight (reported like an unused
    disable)."""
    _, problems = parse_annotations(ctx.text)
    for line, msg in problems:
        yield ctx.finding(line, "unused-suppression", msg)
    for begin, end, label in ctx.annotations:
        # live = some statement in range runs in an async function that
        # ENCLOSES the region (a def nested inside the region executes
        # later, not while the section does — its awaits don't count,
        # so it can't make the claim checkable either)
        live = any(
            begin <= getattr(node, "lineno", 0) <= end
            and (fn := ctx.async_owner(node)) is not None
            and fn.lineno <= begin
            for node in ast.walk(ctx.tree) if isinstance(node, ast.stmt))
        if not live:
            yield ctx.finding(
                begin, "unused-suppression",
                "atomic-section%s covers no statement in an async "
                "function: nothing here can await, so the annotation "
                "verifies nothing"
                % (" %r" % label if label else ""))


# ---- core per-file run ----

def check_source(text: str, path: str = "<string>",
                 config: Config | None = None,
                 summaries=None) -> LintResult:
    config = config or Config()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        f = Finding(path, e.lineno or 0, "syntax",
                    "syntax error: %s" % e.msg)
        return LintResult(path, [f], [])
    except ValueError as e:        # e.g. source with null bytes
        return LintResult(path, [Finding(path, 0, "syntax", str(e))], [])
    ctx = FileContext(path, text, tree, config)
    if summaries is not None:
        ctx.summaries = summaries
    disabled = config.disabled_for(path)
    findings: list[Finding] = []
    before_allow = set(_ALLOW_USED)
    for r in RULES.values():
        if r.name in disabled:
            continue
        findings.extend(r.fn(ctx))
    allow_used = sorted(_ALLOW_USED - before_allow)
    supp = parse_suppressions(text)
    kept, suppressed = [], []
    used: dict[int, set] = {}
    for f in sorted(findings):
        names = supp.get(f.line, ())
        if "all" in names or f.rule in names:
            suppressed.append(f)
            used.setdefault(f.line, set()).add(
                f.rule if f.rule in names else "all")
        else:
            kept.append(f)
    if "unused-suppression" not in disabled:
        # a disable that silenced nothing is stale debt; reported
        # OUTSIDE the suppression match so it cannot silence itself.
        # Names for rules disabled by config are skipped: the comment
        # documents intent for profiles where the rule IS on, and a
        # path-disable must not turn it into a finding.
        for line, names in sorted(supp.items()):
            for name in sorted(names - used.get(line, set()) - disabled):
                what = "disable=all" if name == "all" \
                    else "suppression for %r" % name
                kept.append(ctx.finding(
                    line, "unused-suppression",
                    "%s matches no finding on this line — remove it "
                    "(stale suppressions hide future regressions)"
                    % what))
        kept.extend(_annotation_accounting(ctx))
        kept.sort()
    return LintResult(path, kept, suppressed, allow_used)


def check_file(path: Path, config: Config | None = None,
               summaries=None) -> LintResult:
    try:
        text = path.read_text()
    except UnicodeDecodeError:
        return LintResult(str(path),
                          [Finding(str(path), 0, "syntax", "not utf-8")],
                          [])
    except OSError as e:
        return LintResult(str(path),
                          [Finding(str(path), 0, "syntax",
                                   "unreadable: %s" % e)], [])
    return check_source(text, str(path), config, summaries)


# ---- file iteration ----

def _is_python_script(p: Path) -> bool:
    try:
        head = p.open("rb").readline()
    except OSError:
        return False
    return head.startswith(b"#!") and b"python" in head


def _excluded(p: Path, config: Config) -> bool:
    s = str(p)
    return any(part in s for part in config.exclude)


def iter_files(paths, config: Config) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            found = sorted(p.rglob("*.py"))
            # shebang scripts without .py (tools/lint itself, tools/
            # mkdevcluster, tests/fakepg/postgres, ...) are gated too
            found += sorted(
                f for f in p.rglob("*")
                if f.is_file() and f.suffix == "" and _is_python_script(f))
            for f in found:
                if not _excluded(f, config):
                    yield f
        elif p.is_file() and (p.suffix == ".py" or _is_python_script(p)):
            # explicit file arguments bypass the exclude list
            yield p


def check_paths(paths, config: Config | None = None,
                cache: "ResultCache | None" = None,
                summaries=None) -> tuple[int, list, list]:
    """(files checked, findings, suppressed findings) over *paths*.

    With ``interproc`` on and no *summaries* database supplied, one is
    built over *paths* first (reusing per-file facts from *cache*) so
    every rule sees the same project-wide call graph."""
    config = config or Config()
    if summaries is None and config.interproc:
        from manatee_tpu.lint import summaries as summod
        summaries = summod.SummaryDB.build(paths, config, cache)
    if cache is not None:
        cache.summaries = summaries
    n = 0
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    allow_used: set = set()
    for f in iter_files(paths, config):
        n += 1
        res = cache.lookup(f) if cache is not None else None
        if res is None:
            res = check_file(f, config, summaries)
            if cache is not None:
                cache.store(f, res)
        findings.extend(res.findings)
        suppressed.extend(res.suppressed)
        allow_used.update(res.allow_used)
    _ALLOW_USED.update(allow_used)
    return n, findings, suppressed


# ---- content-hash result cache (--cache) ----

class ResultCache:
    """Per-path lint results and interprocedural facts keyed on a
    content hash.

    The key folds in the file bytes, the effective config, and a digest
    of the lint package's own sources — editing a rule invalidates
    everything, editing one file invalidates that file.  Stored as JSON,
    one entry per path; entries for files that no longer exist are
    pruned at save() time.

    Two layers with different invalidation:

    - ``facts``: per-file extraction output for the summary database
      (callgraph declaration + local function facts).  Depends only on
      that file's content, so an unchanged file never re-parses even
      when its callees changed — the fixpoint re-runs in memory.
    - ``entries``: per-file lint RESULTS.  A result consumed summaries
      of functions in OTHER files, so each entry also records a ``deps``
      map (callee fqn -> summary digest); at lookup time every recorded
      digest must match the freshly-computed summary database, which is
      exactly the "my callee changed may-block under me" case the v3
      cache could not see.
    """

    def __init__(self, path: str | Path, config: Config):
        self.path = Path(path)
        self.salt = hashlib.sha256(
            (_tool_digest() + _config_digest(config)).encode()).hexdigest()
        self.entries: dict[str, dict] = {}
        self.facts: dict[str, dict] = {}
        self.summaries = None         # set by check_paths after build
        self.hits = 0
        self.misses = 0
        try:
            data = json.loads(self.path.read_text())
            if isinstance(data, dict) and data.get("salt") == self.salt:
                self.entries = data.get("entries", {})
                self.facts = data.get("facts", {})
        except (OSError, ValueError):
            pass

    def _key(self, path: Path) -> str | None:
        try:
            blob = path.read_bytes()
        except OSError:
            return None
        return hashlib.sha256(self.salt.encode() + blob).hexdigest()

    def lookup_facts(self, path: Path) -> dict | None:
        """Cached extraction facts for *path*, content-validated."""
        ent = self.facts.get(str(path))
        if not ent or ent.get("key") != self._key(path):
            return None
        return ent["facts"]

    def store_facts(self, path: Path, facts: dict):
        key = self._key(path)
        if key is not None:
            self.facts[str(path)] = {"key": key, "facts": facts}

    def _deps_fresh(self, ent: dict) -> bool:
        deps = ent.get("deps")
        if not deps:
            return True
        if self.summaries is None:
            return False
        return all(self.summaries.digest(fqn) == dig
                   for fqn, dig in deps.items())

    def lookup(self, path: Path) -> LintResult | None:
        ent = self.entries.get(str(path))
        if not ent or ent.get("key") != self._key(path) \
                or not self._deps_fresh(ent):
            self.misses += 1
            return None
        self.hits += 1
        return LintResult(
            str(path),
            [Finding(**d) for d in ent["findings"]],
            [Finding(**d) for d in ent["suppressed"]],
            list(ent.get("allow_used", ())))

    def store(self, path: Path, res: LintResult):
        key = self._key(path)
        if key is None:
            return
        ent = {
            "key": key,
            "findings": [f.as_dict() for f in res.findings],
            "suppressed": [f.as_dict() for f in res.suppressed],
            "allow_used": list(res.allow_used),
        }
        if self.summaries is not None:
            ent["deps"] = self.summaries.file_deps(str(path))
        self.entries[str(path)] = ent

    def save(self):
        # entries whose file is gone (renames, deletions) are dropped
        # here, so the cache tracks the live tree instead of growing
        # with every path that ever existed
        self.entries = {p: ent for p, ent in self.entries.items()
                        if Path(p).is_file()}
        self.facts = {p: ent for p, ent in self.facts.items()
                      if Path(p).is_file()}
        try:
            self.path.write_text(json.dumps(
                {"salt": self.salt, "entries": self.entries,
                 "facts": self.facts},
                sort_keys=True))
        except OSError as e:
            print("mnt-lint: cannot write cache %s: %s"
                  % (self.path, e), file=sys.stderr)


def _tool_digest() -> str:
    """Digest of the lint package sources: any rule/engine edit must
    invalidate every cached result."""
    h = hashlib.sha256()
    pkg = Path(__file__).parent
    for f in sorted(pkg.glob("*.py")):
        h.update(f.name.encode())
        try:
            h.update(f.read_bytes())
        except OSError:
            pass
    return h.hexdigest()


def _config_digest(config: Config) -> str:
    def enc(v):
        if isinstance(v, frozenset):
            return sorted(enc(x) for x in v)
        if isinstance(v, tuple):
            return [enc(x) for x in v]
        return v
    return json.dumps(
        {f.name: enc(getattr(config, f.name))
         for f in dataclasses.fields(config)}, sort_keys=True)


# ---- --changed: lint only files git considers modified ----

def changed_files(base: str | None = None) -> list[str]:
    """Paths changed vs *base* (default: the working tree + index vs
    HEAD) plus untracked files, repo-relative."""
    cmds = [
        ["git", "diff", "--name-only", base or "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ]
    out: set[str] = set()
    for cmd in cmds:
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
        except OSError as e:
            raise SystemExit("mnt-lint: cannot run git: %s" % e)
        if proc.returncode != 0:
            raise SystemExit("mnt-lint: %s failed: %s"
                             % (" ".join(cmd), proc.stderr.strip()))
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return sorted(out)


def _within(path: str, roots) -> bool:
    p = Path(path)
    for root in roots:
        r = Path(root)
        if p == r:
            return True
        try:
            p.relative_to(r)
            return True
        except ValueError:
            continue
    return False


def select_changed(roots, config: Config, base: str | None = None
                   ) -> list[Path]:
    """The lintable subset of git-changed files under *roots*: same
    .py/shebang gating and exclude list as a directory walk."""
    picked = []
    for rel in changed_files(base):
        p = Path(rel)
        if not p.is_file():
            continue             # deleted/renamed-away
        if not _within(rel, roots):
            continue
        if _excluded(p, config):
            continue
        if p.suffix == ".py" or _is_python_script(p):
            picked.append(p)
    return picked


# ---- allowlist matching (used by unbounded-wait and friends) ----

# entries that matched at least once this process — check_source diffs
# this around the rule runs so every LintResult carries the allowlist
# entries it consumed, and a full run can report the never-consumed
# ones as unused-suppression findings against the config file itself
_ALLOW_USED: set = set()


def allow_matches(entries, path: str, funcname: str) -> bool:
    """True when any "<path-glob>::<func-glob>" entry matches.  The path
    part matches against the end of the reported path so entries stay
    stable regardless of how the tool was invoked."""
    hit = False
    for entry in entries:
        pat_path, sep, pat_fn = entry.partition("::")
        if not sep:
            pat_path, pat_fn = entry, "*"
        if not fnmatch.fnmatch(funcname or "", pat_fn):
            continue
        if fnmatch.fnmatch(path, pat_path) \
                or fnmatch.fnmatch(path, "*" + pat_path.lstrip("*")):
            _ALLOW_USED.add(entry)
            hit = True
    return hit


# ---- SARIF output (--format sarif) ----

SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def to_sarif(findings, suppressed) -> dict:
    """One SARIF 2.1.0 run: kept findings as plain results, suppressed
    ones carried with an ``inSource`` suppression record so code
    scanning shows the debt without gating on it."""
    def result(f: Finding, suppressed_in_source: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        }
        if suppressed_in_source:
            out["suppressions"] = [{"kind": "inSource"}]
        return out

    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mnt-lint",
                "informationUri":
                    "https://github.com/TritonDataCenter/manatee",
                "rules": [
                    {"id": name,
                     "shortDescription": {"text": RULES[name].summary}}
                    for name in sorted(RULES)],
            }},
            "results": [result(f, False) for f in findings]
                       + [result(f, True) for f in suppressed],
        }],
    }


# ---- CLI ----

def _build_config(args) -> Config:
    cfg = Config()
    cfg_path = args.config
    if cfg_path is None and Path(".mnt-lint.json").is_file():
        cfg_path = ".mnt-lint.json"
    if cfg_path:
        cfg = Config.from_file(cfg_path, cfg)
    overrides = {}
    if args.max_line is not None:
        overrides["max_line"] = args.max_line
    if args.disable:
        names = set(cfg.disable)
        for chunk in args.disable:
            names.update(n.strip() for n in chunk.split(",") if n.strip())
        unknown = names - set(RULES)
        if unknown:
            raise SystemExit("mnt-lint: unknown rule(s): %s"
                             % ", ".join(sorted(unknown)))
        overrides["disable"] = frozenset(names)
    if args.unbounded_allow:
        overrides["unbounded_allow"] = (cfg.unbounded_allow
                                        | frozenset(args.unbounded_allow))
    return dataclasses.replace(cfg, **overrides)


def _unused_allow_findings(args, config: Config) -> list:
    """Allowlist entries no rule consumed during a full run, reported
    as unused-suppression findings against the config file: allowlist
    debt follows the same no-stale-exemptions contract as inline
    disables."""
    src = args.config \
        or (".mnt-lint.json" if Path(".mnt-lint.json").is_file()
            else "<config>")
    out = []
    for key, entries in (
            ("acquire-discard-allow", config.acquire_discard_allow),
            ("unbounded-allow", config.unbounded_allow),
            ("blocking-by-design", config.blocking_by_design)):
        for entry in sorted(entries):
            if entry not in _ALLOW_USED:
                out.append(Finding(
                    src, 0, "unused-suppression",
                    "%s entry %r matched no finding in a full run — "
                    "remove it (stale allowlist entries hide future "
                    "regressions)" % (key, entry)))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mnt-lint",
        description="stdlib static checks incl. async-concurrency rules "
                    "(docs/lint.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to check (default: the repo tree)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE[,RULE...]",
                    help="disable rules by name")
    ap.add_argument("--config", metavar="FILE",
                    help="JSON config (default: ./.mnt-lint.json if "
                         "present)")
    ap.add_argument("--max-line", type=int, default=None)
    ap.add_argument("--unbounded-allow", action="append", default=[],
                    metavar="PATH::FUNC",
                    help="allowlist entry for the unbounded-wait rule")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="BASE",
                    help="lint only files git reports changed vs BASE "
                         "(default HEAD) plus untracked files, within "
                         "the given paths")
    ap.add_argument("--cache", nargs="?", const=DEFAULT_CACHE,
                    default=None, metavar="FILE",
                    help="reuse results for unchanged file content "
                         "(key: file bytes + config + lint sources; "
                         "default file %s)" % DEFAULT_CACHE)
    ap.add_argument("--suppression-baseline", metavar="FILE",
                    help="JSON {\"suppressed\": N}: fail when the "
                         "suppressed-finding count exceeds N (zero "
                         "NEW suppressions vs the committed baseline)")
    ap.add_argument("--stats", metavar="FILE",
                    help="write run statistics (call-graph size, "
                         "summary counts, cache hit rates, wall time) "
                         "as JSON to FILE")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name in sorted(RULES):
            print("%-*s  %s" % (width, name, RULES[name].summary))
        return 0

    config = _build_config(args)
    roots = args.paths or DEFAULT_PATHS
    cache = ResultCache(args.cache, config) if args.cache else None
    t0 = time.monotonic()
    summaries = None
    if config.interproc:
        from manatee_tpu.lint import summaries as summod
        # the database always spans the full roots: a --changed run
        # still needs the unchanged callees' summaries to judge the
        # change (that's the whole point of interprocedural analysis)
        summaries = summod.SummaryDB.build(roots, config, cache)
    if args.changed is not None:
        targets = select_changed(roots, config, args.changed)
        if not targets:
            print("mnt-lint: no changed files under %s"
                  % ", ".join(map(str, roots)), file=sys.stderr)
    else:
        targets = roots
    n, findings, suppressed = check_paths(targets, config, cache,
                                          summaries)
    if args.changed is None and not args.paths \
            and "unused-suppression" not in config.disable:
        # only a full default-roots run can prove an allowlist entry
        # dead; targeted runs see too few candidate sites to judge
        findings.extend(_unused_allow_findings(args, config))
        findings.sort()
    if cache is not None:
        cache.save()
    rc = 1 if findings else 0
    if args.suppression_baseline:
        try:
            baseline = json.loads(Path(
                args.suppression_baseline).read_text())
            allowed = int(baseline["suppressed"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            raise SystemExit("mnt-lint: bad suppression baseline %s: %s"
                             % (args.suppression_baseline, e))
        if len(suppressed) > allowed:
            print("mnt-lint: %d suppressions exceed the committed "
                  "baseline of %d (%s) — fix the findings instead of "
                  "suppressing them, or justify a baseline bump in "
                  "review" % (len(suppressed), allowed,
                              args.suppression_baseline),
                  file=sys.stderr)
            rc = 1
    if args.format == "json":
        print(json.dumps({
            "files": n,
            "problems": len(findings),
            "findings": [f.as_dict() for f in findings],
            "suppressed": [f.as_dict() for f in suppressed],
        }, indent=2, sort_keys=True))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(findings, suppressed), indent=2,
                         sort_keys=True))
        # stdout is usually redirected into the upload file; keep the
        # job log actionable by rendering the findings on stderr too
        for f in findings:
            print(f.render(), file=sys.stderr)
        summary = "mnt-lint: %d files, %d problems (%d suppressed)" \
            % (n, len(findings), len(suppressed))
        if cache is not None:
            summary += " [cache: %d hits, %d misses]" % (cache.hits,
                                                         cache.misses)
        summary += " in %.1fs" % (time.monotonic() - t0)
        print(summary, file=sys.stderr)
    else:
        for f in findings:
            print(f.render())
        summary = "mnt-lint: %d files, %d problems (%d suppressed)" \
            % (n, len(findings), len(suppressed))
        if cache is not None:
            summary += " [cache: %d hits, %d misses]" % (cache.hits,
                                                         cache.misses)
        summary += " in %.1fs" % (time.monotonic() - t0)
        print(summary, file=sys.stderr)
    if args.stats:
        stats = {
            "files": n,
            "problems": len(findings),
            "suppressed": len(suppressed),
            "wall_ms": int((time.monotonic() - t0) * 1000),
            "result_cache": ({"hits": cache.hits,
                              "misses": cache.misses}
                             if cache is not None else None),
            "summaries": (summaries.stats()
                          if summaries is not None else None),
        }
        try:
            Path(args.stats).write_text(
                json.dumps(stats, indent=2, sort_keys=True) + "\n")
        except OSError as e:
            print("mnt-lint: cannot write stats %s: %s"
                  % (args.stats, e), file=sys.stderr)
    return rc
