"""Rules that only exist because of the summary layer: findings whose
evidence lives entirely in OTHER functions.

``transitive-blocking-in-async`` is the static half of the
``obs.loop.stall`` contract (docs/lint.md, docs/observability.md): any
call chain the runtime watchdog could catch blocking the loop must be
derivable here, and vice versa — a stall whose culprit these summaries
cannot derive is journaled as an ``obs.lint.discrepancy`` by
obs/profile.py.
"""

from __future__ import annotations

import ast

from manatee_tpu.lint.engine import FileContext, dotted, rule
from manatee_tpu.lint.rules_async import _sync_calls_in_async
from manatee_tpu.lint.summaries import is_blocking_name

RULE_TRANSITIVE = "transitive-blocking-in-async"
RULE_SWALLOW_TRANS = "cancellation-swallowed-transitively"


def _render_chain(db, fqn: str, kind: str = "block") -> str:
    links = db.chain(fqn, kind)
    return " -> ".join(links) if links else fqn


@rule(RULE_TRANSITIVE,
      "sync helper chain that blocks, called from a coroutine")
def transitive_blocking_in_async(ctx: FileContext):
    """``blocking-call-in-async`` sees ``time.sleep`` spelled at the
    call site; it cannot see ``self._persist()`` three frames above it.
    This rule resolves every un-awaited call inside a coroutine through
    the project call graph and flags the ones whose summary proves the
    chain reaches the blocking catalog — with the full witness chain in
    the message, because the fix usually belongs at the BOTTOM of the
    chain (or the whole helper belongs in ``asyncio.to_thread``, which
    breaks the call edge and the finding with it).  Chains that end
    only in ``blocking-by-design`` config entries (documented
    deliberate blocking, e.g. dirstore's no-await meta RMW) are not
    reported; the may_block summary itself stays whole, so the
    runtime stall watchdog still derives those stalls."""
    db = ctx.summaries
    if db is None:
        return
    owners = ctx.owners
    for node in _sync_calls_in_async(ctx):
        name = dotted(node.func)
        if name is None:
            continue
        attr = node.func.attr \
            if isinstance(node.func, ast.Attribute) else None
        if is_blocking_name(db.canonical(ctx.path, name), attr,
                            ctx.config):
            continue             # direct hit: the v1 rules own it
        s = db.resolve_call(ctx.path, owners.get(node), name)
        if s is None or s.is_async or not s.reportable_block:
            continue
        yield ctx.finding(
            node.lineno, RULE_TRANSITIVE,
            "%s() transitively blocks the event loop: %s — make the "
            "chain async, or push the whole helper into "
            "run_in_executor/to_thread" % (name,
                                           _render_chain(db, s.fqn)))


@rule(RULE_SWALLOW_TRANS,
      "awaited helper whose generic except eats CancelledError")
def cancellation_swallowed_transitively(ctx: FileContext):
    """``swallowed-cancellation`` flags the generic ``except`` where it
    is written; this flags the *await* that trusts it.  Awaiting a
    coroutine that swallows cancellation means a ``.cancel()`` on THIS
    task can vanish inside the callee — the canceller hangs while this
    frame keeps running.  In a clean tree the base rule keeps the
    callee-side finding from ever existing, so this rule fires only
    when the swallow is suppressed or path-disabled somewhere else —
    exactly the hole a caller cannot see."""
    db = ctx.summaries
    if db is None:
        return
    owners = ctx.owners
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Await) \
                or not isinstance(node.value, ast.Call):
            continue
        owner = owners.get(node)
        if not isinstance(owner, ast.AsyncFunctionDef):
            continue
        name = dotted(node.value.func)
        if name is None:
            continue
        s = db.resolve_call(ctx.path, owner, name)
        if s is None or not s.swallows:
            continue
        yield ctx.finding(
            node.lineno, RULE_SWALLOW_TRANS,
            "awaiting %s() can swallow this task's cancellation: %s — "
            "re-raise CancelledError in the callee (or cancel-shield "
            "deliberately and say so)"
            % (name, _render_chain(db, s.fqn, "swallow")))
