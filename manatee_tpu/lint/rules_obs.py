"""Observability catalog drift: emitted names must be documented.

docs/observability.md carries the metric and journal-event catalogs the
operator tooling (and the SLO dashboards built on top) navigate by.
The faults catalog is drift-proof because ``faultpoint-unregistered``
makes an uncataloged name a lint error; this module gives metric names
and journal event types the same property, in both directions:

- the rule here flags any ``*.counter/gauge/histogram("name", ...)`` or
  ``journal.record("event", ...)`` whose literal name is absent from
  the doc's backtick-quoted catalog entries;
- tests/test_obs_catalog.py (tier-1) sweeps the production tree with
  the same collector, so the contract holds even for files a targeted
  lint run skipped.

Computed names are skipped — except the constant-prefix forms
(``"coord.session." + event``, f-strings with a literal head), which
are checked as prefixes against the catalog (the doc documents those
families as ``coord.session.connected|disconnected|expired``).
"""

from __future__ import annotations

import ast
from pathlib import Path

from manatee_tpu.lint.engine import FileContext, dotted, rule

RULE = "obs-name-undocumented"

DOC = "docs/observability.md"
_DOC_PATH = Path(__file__).resolve().parents[2] / DOC

# receivers that identify the metric registry / the journal
_REGISTRY_RECV = {"_REG", "reg", "_registry", "registry"}
_METRIC_METHODS = {"counter", "gauge", "histogram"}


def _recv_kind(func: ast.Attribute) -> str | None:
    """'metric' / 'journal' when the call receiver is the metrics
    registry or the event journal, else None."""
    recv = func.value
    if isinstance(recv, ast.Call):
        inner = dotted(recv.func)
        last = inner.rsplit(".", 1)[-1] if inner else ""
        if last == "get_registry" and func.attr in _METRIC_METHODS:
            return "metric"
        if last == "get_journal" and func.attr == "record":
            return "journal"
        return None
    name = dotted(recv)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    if last in _REGISTRY_RECV and func.attr in _METRIC_METHODS:
        return "metric"
    if last.endswith("journal") and func.attr == "record":
        return "journal"
    return None


def _literal_or_prefix(arg) -> tuple:
    """('name', s) for a string literal, ('prefix', s) for a constant
    head of a computed name, (None, None) otherwise."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return ("name", arg.value)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add) \
            and isinstance(arg.left, ast.Constant) \
            and isinstance(arg.left.value, str):
        return ("prefix", arg.left.value)
    if isinstance(arg, ast.JoinedStr) and arg.values \
            and isinstance(arg.values[0], ast.Constant) \
            and isinstance(arg.values[0].value, str):
        return ("prefix", arg.values[0].value)
    return (None, None)


def collect_obs_names(tree) -> list:
    """[(kind, 'name'|'prefix', value, line)] for every metric
    registration and journal record in *tree* — the single collector
    the lint rule and the tier-1 sync test share."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        kind = _recv_kind(node.func)
        if kind is None or not node.args:
            continue
        how, value = _literal_or_prefix(node.args[0])
        if how is None or not value:
            continue
        out.append((kind, how, value, node.lineno))
    return out


def documented_names(text: str) -> set:
    """Every backtick-quoted token in the doc, with the catalog's
    ``a.b.c|d|e`` / ``a_b|c`` alternation expanded (each alternative
    replaces the last dotted/underscored segment)."""
    names: set = set()
    for raw in _backtick_tokens(text):
        parts = raw.split("|")
        names.add(parts[0])
        if len(parts) > 1:
            head = parts[0]
            for sep in (".", "_"):
                if sep in head:
                    stem = head.rsplit(sep, 1)[0]
                    for alt in parts[1:]:
                        names.add(stem + sep + alt)
                    break
            else:
                names.update(parts[1:])
    return names


def _backtick_tokens(text: str):
    out = []
    cur = None
    for ch in text:
        if ch == "`":
            if cur is None:
                cur = []
            else:
                tok = "".join(cur).strip()
                if tok:
                    out.append(tok)
                cur = None
        elif cur is not None:
            cur.append(ch)
    return out


def _doc_names() -> set | None:
    try:
        return documented_names(_DOC_PATH.read_text())
    except OSError:
        return None


@rule(RULE, "metric/journal name missing from the observability "
            "catalog (%s)" % DOC)
def obs_name_undocumented(ctx: FileContext):
    documented = _doc_names()
    if documented is None:
        return                   # no doc checkout: nothing to enforce
    for kind, how, value, line in collect_obs_names(ctx.tree):
        label = "metric" if kind == "metric" else "journal event"
        if how == "name":
            if value in documented:
                continue
            yield ctx.finding(
                line, RULE,
                "%s %r is not in the %s catalog — document it there "
                "(name, type/labels, meaning) or stop emitting it"
                % (label, value, DOC))
        else:                    # constant prefix of a computed name
            if any(d.startswith(value) for d in documented):
                continue
            yield ctx.finding(
                line, RULE,
                "computed %s name with prefix %r matches nothing in "
                "the %s catalog — document the family (e.g. "
                "'%s...') or emit a cataloged literal"
                % (label, value, DOC, value))
