"""The original mnt-lint checks, carried over as engine rules.

These are the style/correctness checks the seed ``tools/lint`` shipped
with (no third-party linters ship in the dev image; the reference gates
on jsl + jsstyle, Makefile:60-66).  Syntax is engine-level: a file that
does not parse yields a single ``syntax`` finding and no rule runs.
"""

from __future__ import annotations

import ast

from manatee_tpu.lint.engine import FileContext, rule


class _ImportVisitor(ast.NodeVisitor):
    """Collect imported names and all referenced names per module."""

    def __init__(self):
        self.imports: dict[str, ast.stmt] = {}
        self.used: set[str] = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


@rule("unused-import", "module-level import never referenced")
def unused_import(ctx: FileContext):
    """Module scope only: function-level imports are often deliberate
    lazy loads here.  Names listed in __all__ count as used (re-export
    modules); other string literals do NOT — a docstring mentioning a
    module name must not disable the check for it."""
    iv = _ImportVisitor()
    iv.visit(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets):
            for c in ast.walk(node.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    iv.used.add(c.value)
    for name, node in iv.imports.items():
        if name not in iv.used and not name.startswith("_"):
            yield ctx.finding(node.lineno, "unused-import",
                              "unused import %r" % name)


def _is_accessor_overload(child) -> bool:
    """``@x.setter``/``@x.getter``/``@x.deleter`` (and
    ``@singledispatch``-style ``@x.register``) deliberately redefine
    ``x`` — the decorator consumes the previous binding."""
    for dec in child.decorator_list:
        if isinstance(dec, ast.Call):
            dec = dec.func
        if isinstance(dec, ast.Attribute) \
                and dec.attr in ("setter", "getter", "deleter",
                                 "register"):
            return True
    return False


@rule("shadowed-def", "duplicate def/class in the same scope")
def shadowed_def(ctx: FileContext):
    """A shadowed def is almost always a copy-paste bug."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.ClassDef, ast.Module)):
            continue
        names: dict[str, int] = {}
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                key = child.name
                if key in names and not key.startswith("_dup_ok") \
                        and not _is_accessor_overload(child):
                    yield ctx.finding(
                        child.lineno, "shadowed-def",
                        "%r shadows definition at line %d"
                        % (key, names[key]))
                names[key] = child.lineno


@rule("bare-except", "except: with no exception type")
def bare_except(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield ctx.finding(node.lineno, "bare-except", "bare except")


@rule("mutable-default", "mutable default argument")
def mutable_default(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    yield ctx.finding(
                        node.lineno, "mutable-default",
                        "mutable default argument in %s()" % node.name)


@rule("style", "tabs, trailing whitespace, long lines")
def style(ctx: FileContext):
    max_line = ctx.config.max_line
    for i, line in enumerate(ctx.lines, 1):
        if "\t" in line:
            yield ctx.finding(i, "style", "tab character")
        if line != line.rstrip():
            yield ctx.finding(i, "style", "trailing whitespace")
        if len(line) > max_line:
            yield ctx.finding(i, "style", "line too long (%d > %d)"
                              % (len(line), max_line))
