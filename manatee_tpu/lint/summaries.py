"""Per-function interprocedural summaries, computed to fixpoint.

The v3 flow rules analyze one function at a time, so a helper call is
an opaque event: an await, a blocking syscall, or a resource
acquisition one call level down is invisible.  This module closes that
hole.  For every function def in the project it computes a
:class:`Summary` with:

- **may_suspend** — the function (async) transitively contains a real
  suspension point.  ``await g()`` where ``g`` is a project coroutine
  that never suspends runs inline without yielding to the loop, so the
  flow rules can stop treating such awaits as interleave points;
- **may_block** — transitively reaches a call in the blocking catalog
  (``time.sleep``, subprocess, sync file I/O, ...) on the calling
  thread, with a witness chain for the message and for the runtime
  stall cross-check (obs/profile.py);
- **swallows_cancellation** — a generic except arm around awaits, or an
  awaited callee that has one: awaiting this function can absorb a
  cancel;
- **returns_resource** — a handle from an acquire call (config
  ``acquire-calls``) flows to the return value: calling this function
  IS acquiring, so cancel-unsafe-acquire treats the call site as the
  acquisition;
- **param_effects** — per parameter: ``closed`` (a close method or
  ``with`` scope), ``escaped`` (returned / stored / aliased),
  ``unknown`` (passed to something unresolvable — protective, sound),
  or ``leaked`` (none of the above on any path: passing a handle here
  is NOT an ownership transfer);
- **lock-effects** — locks acquired/released, locks held for the whole
  body, and ``required_held``: locks every same-class resolved call
  site provably holds around the call (windows inside such a helper
  are already guarded by the callers);
- **save_calls / load_returns** — the function performs a
  ``*save*``-glob state write with a parameter as the value (or
  returns a ``*load*``-glob read), letting atomic-section-broken pair
  load-modify-save windows through one helper level.

Soundness contract (see docs/lint.md): every fact is *may* (or, for
``required_held``/``param_effects`` protections, *must*) information
with the default chosen so an UNRESOLVED call behaves exactly like the
opaque call v3 assumed — sharper resolution can only remove false
negatives or false positives, never add unsound silence.  Extraction
is purely per-file (content-cacheable); resolution and the fixpoint
always re-run in memory over the whole graph.
"""

from __future__ import annotations

import ast
import hashlib
import json

from manatee_tpu.lint import callgraph as cg
from manatee_tpu.lint.engine import (
    Config,
    allow_matches,
    dotted,
    iter_files,
    walk_no_defs,
)

# ---- shared catalogs (single source for rules_async + summaries) ----

BLOCKING_CALLS = frozenset({
    "time.sleep",
    "os.system", "os.popen", "os.wait", "os.waitpid",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "subprocess.getstatusoutput", "subprocess.Popen",
    "socket.create_connection", "socket.getaddrinfo",
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.head", "requests.request",
})
# sync file I/O: the open() builtin plus pathlib-style method names
BLOCKING_IO_CALLS = frozenset({"open"})
BLOCKING_IO_METHODS = frozenset({
    "read_text", "write_text", "read_bytes", "write_bytes",
})
# methods that close/terminate a handle (shared with rules_flow)
CLOSE_METHODS = frozenset({
    "close", "aclose", "terminate", "kill", "release", "cancel",
    "unlink", "wait_closed", "shutdown", "stop", "abort", "detach",
})
_ACQ_WRAPPERS = frozenset({"wait_for", "shield"})
_GENERIC_EXC = frozenset({"Exception", "BaseException"})

# witness chains and fixpoint rounds are bounded (cycles in the call
# graph converge anyway; these keep pathological graphs cheap)
_CHAIN_BOUND = 12
_ROUND_BOUND = 100


def _name_match(entries, name: str | None) -> bool:
    if not name:
        return False
    for entry in entries:
        if "." in entry:
            if name == entry:
                return True
        elif name == entry or name.endswith("." + entry):
            return True
    return False


def is_blocking_name(name: str | None, attr: str | None,
                     config: Config) -> str | None:
    """The catalog entry a (canonicalized) call name hits, or None.
    *attr* is the raw attribute name for method-style I/O."""
    if name and name in (BLOCKING_CALLS | config.blocking_extra):
        return name
    if name and name in BLOCKING_IO_CALLS:
        return name
    if attr and attr in BLOCKING_IO_METHODS:
        return "." + attr
    return None


# ---- per-file fact extraction (content-determined, cacheable) ----

def _mentions(node, names: set) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id in names
               for sub in ast.walk(node))


def _lock_stack(parents: dict, node, fn) -> tuple:
    """Dotted with-locks lexically enclosing *node* within *fn*."""
    out = []
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                d = dotted(item.context_expr)
                if d:
                    out.append(d)
        cur = parents.get(cur)
    return tuple(sorted(set(out)))


def _glob_stem(name: str, globs) -> str | None:
    import fnmatch
    for g in globs:
        if fnmatch.fnmatch(name, g):
            core = g.replace("*", "")
            if core and core in name:
                return name.replace(core, "", 1)
            return name
    return None


def _handler_swallows(try_node: ast.Try) -> int | None:
    """Line of the first generic handler that can eat CancelledError
    (mirrors the swallowed-cancellation rule's arm logic)."""
    cancel_armed = False
    for h in try_node.handlers:
        names = set()
        if h.type is not None:
            nodes = h.type.elts if isinstance(h.type, ast.Tuple) \
                else [h.type]
            for n in nodes:
                d = dotted(n)
                if d:
                    names.add(d.rsplit(".", 1)[-1])
        if "CancelledError" in names:
            cancel_armed = True
            continue
        generic = h.type is None or (names & _GENERIC_EXC)
        if not generic or cancel_armed:
            continue
        if any(isinstance(n, ast.Raise) for s in h.body
               for n in walk_no_defs(s)):
            continue
        return h.lineno
    return None


def _local_has_await(stmts) -> bool:
    for stmt in stmts:
        for node in walk_no_defs(stmt):
            if isinstance(node,
                          (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
    return False


class _FuncExtractor:
    """Local facts for one def: everything the fixpoint needs, no
    resolution, JSON-able output."""

    def __init__(self, path, fn, parents, config: Config):
        self.path = path
        self.fn = fn
        self.parents = parents
        self.config = config
        self.is_async = isinstance(fn, ast.AsyncFunctionDef)

    def run(self) -> dict:
        fn, parents, config = self.fn, self.parents, self.config
        calls = []
        blocking = []
        hard_suspends = False
        swallow_line = None
        save_calls = []
        load_returns = []
        locks_acquired: set = set()
        locks_released: set = set()
        acq_locals: set = set()
        ret_nodes = []
        params = cg._def_params(fn, self._in_class())
        param_set = set(params)
        param_close: set = set()
        param_escape: set = set()
        param_pass: dict = {p: [] for p in params}
        return_acquire = False

        for node in walk_no_defs(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                ret_nodes.append(node.value)
            if isinstance(node, (ast.AsyncFor, ast.AsyncWith)):
                hard_suspends = True
            if isinstance(node, ast.Yield) and self.is_async:
                hard_suspends = True
            if isinstance(node, ast.Await):
                v = node.value
                if not (isinstance(v, ast.Call)
                        and dotted(v.func) is not None):
                    hard_suspends = True
            if isinstance(node, ast.Try) and self.is_async \
                    and swallow_line is None and _local_has_await(node.body):
                swallow_line = _handler_swallows(node)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    d = dotted(item.context_expr)
                    if d:
                        locks_acquired.add(d)
                    # `with p:` scope-protects a parameter handle
                    if isinstance(item.context_expr, ast.Name) \
                            and item.context_expr.id in param_set:
                        param_close.add(item.context_expr.id)
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            attr = node.func.attr \
                if isinstance(node.func, ast.Attribute) else None
            if attr == "release" and name:
                locks_released.add(name.rsplit(".", 1)[0])
            # parameter effects: receiver of a close method, or passed
            # as an argument to another call
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in param_set:
                if attr in CLOSE_METHODS:
                    param_close.add(node.func.value.id)
            for pos, a in enumerate(node.args):
                if isinstance(a, ast.Name) and a.id in param_set:
                    param_pass[a.id].append([name, pos])
            if name is None:
                continue
            awaited = isinstance(self.parents.get(node), ast.Await)
            catalog = is_blocking_name(None if name is None else name,
                                       attr, config)
            # catalog membership is re-checked at fixpoint time with
            # import canonicalization; record the raw hit here for the
            # common spelled-out case
            if catalog and not awaited:
                blocking.append([catalog, node.lineno])
            bound = self._binding_locals(node)
            if _name_match(config.acquire_calls, name):
                if bound:
                    acq_locals.update(bound)
                elif self._in_return(node):
                    # `return open(path)`: the acquire IS the return
                    # value, no local ever binds it
                    return_acquire = True
            calls.append({
                "name": name, "line": node.lineno, "awaited": awaited,
                "bound": sorted(bound),
                "in_return": self._in_return(node),
                "locks": list(_lock_stack(parents, node, fn)),
            })
            stem = _glob_stem(name.rsplit(".", 1)[-1],
                              config.atomic_save_calls)
            if stem is not None and "." in name:
                recv = name.rsplit(".", 1)[0]
                value_args = list(node.args) + [kw.value
                                                for kw in node.keywords]
                value_params = sorted(
                    p for p in param_set
                    if any(_mentions(a, {p}) for a in value_args))
                if value_params:
                    arg0 = None
                    if node.args:
                        a0 = node.args[0]
                        if isinstance(a0, ast.Name) \
                                and a0.id in param_set:
                            arg0 = ["param", a0.id]
                        else:
                            arg0 = ["dump", ast.dump(a0)]
                    save_calls.append({
                        "recv": recv,
                        "stem": stem,
                        "value_params": value_params,
                        "arg0": arg0,
                        "line": node.lineno,
                    })

        # `return <recv>.<load-glob>(args)` (possibly awaited)
        for val in ret_nodes:
            v = val.value if isinstance(val, ast.Await) else val
            if not (isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)):
                continue
            recv = dotted(v.func.value)
            stem = _glob_stem(v.func.attr, config.atomic_load_calls)
            if recv is None or stem is None:
                continue
            arg0 = None
            if v.args:
                a0 = v.args[0]
                if isinstance(a0, ast.Name) and a0.id in param_set:
                    arg0 = ["param", a0.id]
                else:
                    arg0 = ["dump", ast.dump(a0)]
            load_returns.append({"recv": recv, "stem": stem,
                                 "arg0": arg0, "line": v.lineno})

        returns_resource = return_acquire or (any(
            self._escaping_names(val, acq_locals)
            for val in ret_nodes) if acq_locals else False)
        for val in ret_nodes:
            for p in param_set & {n.id for n in ast.walk(val)
                                  if isinstance(n, ast.Name)}:
                param_escape.add(p)
        for node in walk_no_defs(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                stores = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    or isinstance(t, ast.Name)
                    for t in targets)
                if stores:
                    for p in param_set:
                        if _mentions(node.value, {p}):
                            param_escape.add(p)
            elif isinstance(node, ast.Yield) and node.value is not None:
                for p in param_set:
                    if _mentions(node.value, {p}):
                        param_escape.add(p)

        param_local = {}
        for p in params:
            if p in param_close:
                param_local[p] = "closed"
            elif p in param_escape:
                param_local[p] = "escaped"
            elif param_pass[p]:
                param_local[p] = "passed"
            else:
                param_local[p] = "leaked"

        holds = frozenset()
        body = fn.body
        while len(body) == 1 and isinstance(body[0], (ast.With,
                                                      ast.AsyncWith)):
            names = {d for item in body[0].items
                     if (d := dotted(item.context_expr)) is not None}
            holds = holds | names
            body = body[0].body

        return {
            "is_async": self.is_async,
            "line": self.fn.lineno,
            "end_line": getattr(self.fn, "end_lineno", self.fn.lineno),
            "params": params,
            "calls": calls,
            "blocking": blocking,
            "hard_suspends": hard_suspends,
            "swallow_line": swallow_line,
            "returns_resource": returns_resource,
            "param_local": param_local,
            "param_pass": {p: v for p, v in param_pass.items() if v},
            "save_calls": save_calls,
            "load_returns": load_returns,
            "locks_acquired": sorted(locks_acquired),
            "locks_released": sorted(locks_released),
            "holds_throughout": sorted(holds),
        }

    def _in_class(self) -> bool:
        return isinstance(self.parents.get(self.fn), ast.ClassDef)

    def _binding_locals(self, call) -> set:
        """Locals the call's result is bound to, climbing await and
        wait_for/shield wrappers (mirrors rules_flow._binding_of)."""
        cur, parent = call, self.parents.get(call)
        while True:
            if isinstance(parent, ast.Await):
                cur, parent = parent, self.parents.get(parent)
                continue
            if isinstance(parent, ast.Call):
                pname = dotted(parent.func)
                if pname and pname.rsplit(".", 1)[-1] in _ACQ_WRAPPERS \
                        and cur in parent.args:
                    cur, parent = parent, self.parents.get(parent)
                    continue
            break
        if isinstance(parent, ast.Assign) and parent.value is cur \
                and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                return {t.id}
            if isinstance(t, ast.Tuple) and all(
                    isinstance(e, ast.Name) for e in t.elts):
                return {e.id for e in t.elts}
        return set()

    def _in_return(self, call) -> bool:
        cur, parent = call, self.parents.get(call)
        while isinstance(parent, (ast.Await, ast.Tuple, ast.List)):
            cur, parent = parent, self.parents.get(parent)
        return isinstance(parent, ast.Return)

    def _escaping_names(self, val, names: set) -> set:
        """Names from *names* that *val* hands to the caller AS
        THEMSELVES: a bare load, not an attribute read off them —
        ``return proc.returncode`` does not hand over ``proc``, so the
        caller has nothing to close."""
        out = set()
        for n in ast.walk(val):
            if isinstance(n, ast.Name) and n.id in names:
                par = self.parents.get(n)
                if isinstance(par, ast.Attribute) and par.value is n:
                    continue
                out.add(n.id)
        return out


def extract_file_facts(path: str, tree: ast.AST,
                       config: Config) -> dict:
    """Declaration dict + per-def local facts for one file."""
    decl, nodes = cg.scan_module(str(path), tree)
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    funcs = {}
    for qualname, fn in nodes.items():
        funcs[qualname] = _FuncExtractor(path, fn, parents,
                                         config).run()
    return {"decl": decl, "funcs": funcs}


# ---- summaries + fixpoint ----

class Summary:
    """Fixpoint result for one function (see module docstring)."""

    __slots__ = ("fqn", "path", "qualname", "line", "end_line",
                 "is_async", "may_suspend", "may_block", "block_via",
                 "reportable_block", "swallows", "swallow_via",
                 "returns_resource", "resource_via", "param_effects",
                 "save_calls", "load_returns", "locks_acquired",
                 "locks_released", "holds_throughout", "required_held",
                 "callees")

    def __init__(self, fd: cg.FuncDef, facts: dict):
        self.fqn = fd.fqn
        self.path = fd.path
        self.qualname = fd.qualname
        self.line = facts["line"]
        self.end_line = facts["end_line"]
        self.is_async = facts["is_async"]
        self.may_suspend = False
        self.may_block = False
        self.block_via = None      # ("direct", name, line) |
                                   # ("call", fqn, line)
        # may_block minus chains accounted for by blocking-by-design
        # config entries — what transitive-blocking-in-async reports.
        # may_block itself stays whole for the runtime stall contract.
        self.reportable_block = False
        self.swallows = False
        self.swallow_via = None
        self.returns_resource = facts["returns_resource"]
        self.resource_via = "acquire" if self.returns_resource else None
        self.param_effects: dict = {}
        self.save_calls = facts["save_calls"]
        self.load_returns = facts["load_returns"]
        self.locks_acquired = frozenset(facts["locks_acquired"])
        self.locks_released = frozenset(facts["locks_released"])
        self.holds_throughout = frozenset(facts["holds_throughout"])
        self.required_held: frozenset = frozenset()
        self.callees: dict = {}    # fqn -> True (resolved out-edges)

    def digest(self) -> str:
        """Content digest of everything a CALLER can observe; cache
        entries of callers record these per dependency."""
        payload = {
            "suspend": self.may_suspend, "block": self.may_block,
            "reportable": self.reportable_block,
            "swallows": self.swallows,
            "resource": self.returns_resource,
            "params": self.param_effects,
            "saves": self.save_calls, "loads": self.load_returns,
            "req": sorted(self.required_held),
            "holds": sorted(self.holds_throughout),
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()


class SummaryDB:
    """The project-wide summary database rules consult."""

    def __init__(self, config: Config):
        self.config = config
        self.graph = cg.CallGraph()
        self.summaries: dict[str, Summary] = {}
        self._facts: dict[str, dict] = {}     # path -> file facts
        self.trees: dict[str, tuple] = {}     # path -> (text, tree)
        self.facts_hits = 0
        self.facts_misses = 0
        self.rounds = 0
        self.resolved_edges = 0
        self.unresolved_edges = 0

    # -- construction --

    @classmethod
    def build(cls, paths, config: Config, cache=None,
              root=None) -> "SummaryDB":
        """Scan *paths* (directories/files, same walk as the linter),
        reusing per-file facts from *cache* (a ResultCache) when the
        content hash still matches.  *root*, when given, relativizes
        path keys (module names depend on repo-relative paths)."""
        import os
        db = cls(config)
        for f in iter_files(paths, config):
            path = str(f)
            if root is not None:
                try:
                    path = os.path.relpath(path, str(root))
                except ValueError:
                    pass
            facts = cache.lookup_facts(f) if cache is not None else None
            if facts is not None:
                db.facts_hits += 1
                db._facts[path] = facts
                continue
            db.facts_misses += 1
            try:
                text = f.read_text()
                tree = ast.parse(text, filename=path)
            except (OSError, SyntaxError, UnicodeDecodeError,
                    ValueError):
                continue
            db.trees[path] = (text, tree)
            facts = extract_file_facts(path, tree, config)
            db._facts[path] = facts
            if cache is not None:
                cache.store_facts(f, facts)
        db._assemble()
        return db

    @classmethod
    def build_from_sources(cls, files, config: Config) -> "SummaryDB":
        """*files*: iterable of (path, text, tree) already in hand
        (single-file contexts, unit fixtures)."""
        db = cls(config)
        for path, text, tree in files:
            path = str(path)
            db.trees[path] = (text, tree)
            db._facts[path] = extract_file_facts(path, tree, config)
            db.facts_misses += 1
        db._assemble()
        return db

    def _assemble(self):
        for facts in self._facts.values():
            self.graph.add(facts["decl"])
        self._propagate()

    # -- fixpoint --

    def _each_func(self):
        for path, facts in self._facts.items():
            modname = facts["decl"]["name"]
            for qualname, ff in facts["funcs"].items():
                fqn = "%s:%s" % (modname, qualname)
                yield path, fqn, ff

    def _propagate(self):
        graph, config = self.graph, self.config
        bydesign = config.blocking_by_design
        declared: set = set()      # fqns blocking-by-design covers
        # seed summaries + resolve every call edge once
        edges: dict[str, list] = {}
        in_edges: dict[str, list] = {}
        for path, fqn, ff in self._each_func():
            fd = graph.defs.get(fqn)
            if fd is None:
                continue
            s = Summary(fd, ff)
            self.summaries[fqn] = s
            if bydesign and allow_matches(bydesign, fd.path,
                                          fd.qualname):
                declared.add(fqn)
            out = []
            for call in ff["calls"]:
                callee = graph.resolve(fd, path, call["name"])
                if callee is None:
                    self.unresolved_edges += 1
                    # sound default: an awaited call we cannot resolve
                    # (asyncio.sleep, a peer RPC, a queue get) may
                    # genuinely suspend — only a RESOLVED project
                    # coroutine can ever be proven inline
                    if call["awaited"] and s.is_async:
                        s.may_suspend = True
                    # canonicalized catalog check: `sleep(1)` after
                    # `from time import sleep` is a direct block
                    canon = graph.canonical(path, call["name"])
                    attr = call["name"].rsplit(".", 1)[-1] \
                        if "." in call["name"] else None
                    hit = is_blocking_name(canon, attr, config)
                    if hit and not call["awaited"] \
                            and [hit, call["line"]] not in ff["blocking"]:
                        ff["blocking"].append([hit, call["line"]])
                    continue
                self.resolved_edges += 1
                out.append((callee.fqn, call))
                s.callees[callee.fqn] = True
                in_edges.setdefault(callee.fqn, []).append((fqn, call))
            edges[fqn] = out
            if ff["blocking"]:
                name, line = ff["blocking"][0]
                s.may_block = True
                s.block_via = ("direct", name, line)
                s.reportable_block = fqn not in declared
            if ff["hard_suspends"] and s.is_async:
                s.may_suspend = True
            if ff["swallow_line"] is not None and s.is_async:
                s.swallows = True
                s.swallow_via = ("direct", "except",
                                 ff["swallow_line"])

        facts_of = {fqn: ff for _p, fqn, ff in self._each_func()}

        # required_held: private methods whose every same-class
        # resolved call site holds the same lock(s) around the call
        for fqn, s in self.summaries.items():
            fd = graph.defs.get(fqn)
            if fd is None or fd.cls is None \
                    or not fd.name.startswith("_"):
                continue
            callers = in_edges.get(fqn, [])
            if not callers:
                continue
            held = None
            for caller_fqn, call in callers:
                cfd = graph.defs.get(caller_fqn)
                if cfd is None or cfd.cls != fd.cls \
                        or cfd.module != fd.module:
                    held = frozenset()
                    break
                site = frozenset(call["locks"])
                held = site if held is None else (held & site)
            s.required_held = held or frozenset()

        # monotone fixpoint over may_* / swallows / returns_resource /
        # param effects
        for self.rounds in range(1, _ROUND_BOUND + 1):
            changed = False
            for fqn, s in self.summaries.items():
                ff = facts_of.get(fqn)
                if ff is None:
                    continue
                for callee_fqn, call in edges.get(fqn, ()):
                    c = self.summaries.get(callee_fqn)
                    if c is None:
                        continue
                    runs_inline = (not c.is_async) or call["awaited"]
                    if runs_inline and c.may_block and not s.may_block:
                        s.may_block = True
                        s.block_via = ("call", callee_fqn,
                                       call["line"])
                        changed = True
                    if runs_inline and c.reportable_block \
                            and not s.reportable_block \
                            and fqn not in declared:
                        s.reportable_block = True
                        changed = True
                    if s.is_async and call["awaited"] and c.is_async:
                        if c.may_suspend and not s.may_suspend:
                            s.may_suspend = True
                            changed = True
                        if c.swallows and not s.swallows:
                            s.swallows = True
                            s.swallow_via = ("call", callee_fqn,
                                             call["line"])
                            changed = True
                    if call["in_return"] and c.returns_resource \
                            and runs_inline and not s.returns_resource:
                        s.returns_resource = True
                        s.resource_via = callee_fqn
                        changed = True
                # param effects: a pure-pass param is protected when
                # some resolved target protects it; unresolved targets
                # are protective by default (sound)
                for p, local in ff["param_local"].items():
                    if local != "passed":
                        if s.param_effects.get(p) != local:
                            s.param_effects[p] = local
                            changed = True
                        continue
                    cur = s.param_effects.get(p, "leaked")
                    if cur != "leaked":
                        continue
                    effect = "leaked"
                    for callee_name, pos in ff["param_pass"].get(p, ()):
                        fd = self.graph.defs.get(fqn)
                        target = self.graph.resolve(
                            fd, s.path, callee_name)
                        if target is None:
                            effect = "unknown"
                            break
                        tsum = self.summaries.get(target.fqn)
                        tparams = target.params
                        if tsum is None or pos >= len(tparams):
                            effect = "unknown"
                            break
                        te = tsum.param_effects.get(tparams[pos],
                                                    "leaked")
                        if te != "leaked":
                            effect = "unknown"
                            break
                    if effect != cur:
                        s.param_effects[p] = effect
                        changed = True
            if not changed:
                break

    # -- queries --

    def enabled(self) -> bool:
        return True

    def def_for(self, path: str, fn_node) -> cg.FuncDef | None:
        return self.graph.def_at(str(path), fn_node.lineno,
                                 fn_node.name)

    def summary_for(self, path: str, fn_node) -> Summary | None:
        fd = self.def_for(path, fn_node)
        return self.summaries.get(fd.fqn) if fd else None

    def resolve_call(self, path: str, fn_node,
                     name: str | None) -> Summary | None:
        """Summary of the project function a dotted call *name* inside
        *fn_node* refers to (None: unresolved, apply sound default)."""
        caller = self.def_for(path, fn_node) if fn_node is not None \
            else None
        fd = self.graph.resolve(caller, str(path), name)
        return self.summaries.get(fd.fqn) if fd else None

    def canonical(self, path: str, name: str | None) -> str | None:
        return self.graph.canonical(str(path), name)

    def function_at(self, path: str, line: int) -> Summary | None:
        """Innermost def whose span contains *line* in *path*."""
        best = None
        for s in self.summaries.values():
            if s.path == str(path) and s.line <= line <= s.end_line:
                if best is None or s.line > best.line:
                    best = s
        return best

    def chain(self, fqn: str, kind: str = "block") -> list[str]:
        """Human-readable witness chain for a may_block (or swallows)
        fact: ``["a (p.py:3)", "b (q.py:9)", "time.sleep (q.py:12)"]``."""
        out = []
        cur = fqn
        for _ in range(_CHAIN_BOUND):
            s = self.summaries.get(cur)
            if s is None:
                break
            via = s.block_via if kind == "block" else s.swallow_via
            if via is None:
                break
            what, target, line = via
            if what == "direct":
                out.append("%s (%s:%d)" % (target, s.path, line))
                break
            nxt = self.summaries.get(target)
            label = nxt.qualname if nxt else target
            out.append("%s (%s:%d)" % (label, s.path, line))
            cur = target
        return out

    def digest(self, fqn: str) -> str | None:
        s = self.summaries.get(fqn)
        return s.digest() if s else None

    def file_deps(self, path: str) -> dict:
        """fqn -> digest for every summary a cached result for *path*
        depends on: the file's own defs (required_held and friends are
        computed from callers elsewhere) plus every resolved callee."""
        deps: dict[str, str] = {}
        path = str(path)
        for s in self.summaries.values():
            if s.path != path:
                continue
            deps[s.fqn] = s.digest()
            for callee in s.callees:
                c = self.summaries.get(callee)
                if c is not None:
                    deps[callee] = c.digest()
        return deps

    def stats(self) -> dict:
        blocking = sum(1 for s in self.summaries.values()
                       if s.may_block)
        return {
            "modules": len(self.graph.modules),
            "functions": len(self.summaries),
            "resolved_edges": self.resolved_edges,
            "unresolved_edges": self.unresolved_edges,
            "may_block": blocking,
            "may_suspend": sum(1 for s in self.summaries.values()
                               if s.may_suspend),
            "swallows_cancellation": sum(
                1 for s in self.summaries.values() if s.swallows),
            "returns_resource": sum(
                1 for s in self.summaries.values()
                if s.returns_resource),
            "fixpoint_rounds": self.rounds,
            "facts_cache": {"hits": self.facts_hits,
                            "misses": self.facts_misses},
        }


# ---- runtime <-> static cross-check (obs/profile.py) ----

class StaticBlockingAudit:
    """The may-block side of the ``obs.loop.stall`` two-sided contract.

    Built lazily (on the first stall) from the on-disk tree; answers,
    for a stalled frame stack, whether the static analysis *derives*
    the culprit (may_block) and whether the blocking rules were told to
    ignore it (path-disable / inline suppression).  Every journaled
    ``obs.lint.discrepancy`` is one of:

    - ``via=path-disable`` / ``via=suppression``: lint was exempted
      from code that demonstrably blocks the loop;
    - ``via=not-derived``: the stall's culprit frame is NOT derivable
      from the may-block summaries — the static side is blind and one
      of the two must be fixed.
    """

    BLOCK_RULES = ("blocking-call-in-async", "blocking-io-in-async",
                   "transitive-blocking-in-async")

    def __init__(self, root, config: Config | None = None):
        from pathlib import Path
        self.root = Path(root)
        cfg_path = self.root / ".mnt-lint.json"
        if config is None:
            try:
                config = Config.from_file(cfg_path) \
                    if cfg_path.is_file() else Config()
            except (OSError, ValueError):
                config = Config()
        self.config = config
        self._db: SummaryDB | None = None
        self._sup_cache: dict[str, dict] = {}

    @property
    def db(self) -> SummaryDB:
        """The project SummaryDB, built on first use — an exemption
        verdict (path-disable / suppression) never pays for it; only
        the derivability side of the contract does."""
        if self._db is None:
            paths = [self.root / p for p in
                     ("manatee_tpu", "tests", "tools")]
            self._db = SummaryDB.build(
                [p for p in paths if p.exists()], self.config,
                root=self.root)
        return self._db

    def _suppressions(self, rel: str) -> dict:
        from manatee_tpu.lint.engine import parse_suppressions
        sup = self._sup_cache.get(rel)
        if sup is None:
            try:
                sup = parse_suppressions(
                    (self.root / rel).read_text())
            except OSError:
                sup = {}
            self._sup_cache[rel] = sup
        return sup

    def _exemption(self, rel: str, line: int) -> tuple | None:
        off = frozenset(self.BLOCK_RULES) \
            & self.config.disabled_for(rel)
        if off:
            return (sorted(off)[0], "path-disable")
        rules = self._sup_cache_line(rel, line)
        hit = frozenset(self.BLOCK_RULES) & rules
        if not hit and "all" in rules:
            hit = frozenset(self.BLOCK_RULES)
        if hit:
            return (sorted(hit)[0], "suppression")
        return None

    def _sup_cache_line(self, rel: str, line: int) -> frozenset:
        return frozenset(self._suppressions(rel).get(line) or ())

    def derivable(self, rel: str, line: int) -> bool:
        """True when the innermost project frame's function carries a
        may_block summary (the stall was statically predicted)."""
        s = self.db.function_at(rel, line)
        return bool(s is not None and s.may_block)

    def verdict(self, frames) -> dict | None:
        """*frames*: innermost-first (path, line, func) with
        repo-relative paths; a discrepancy dict, or None when the
        static side already accounts for this stall."""
        project = [(p, ln, fn) for p, ln, fn in frames
                   if p.startswith(("manatee_tpu/", "tests/",
                                    "tools/"))]
        if not project:
            return None
        for rel, line, func in project:
            ex = self._exemption(rel, line)
            if ex is not None:
                rule_name, via = ex
                return {"file": rel, "line": line, "func": func,
                        "rule": rule_name, "via": via}
        rel, line, func = project[0]
        if not self.derivable(rel, line):
            return {"file": rel, "line": line, "func": func,
                    "rule": "transitive-blocking-in-async",
                    "via": "not-derived"}
        return None
