"""Train the failure-prediction model and export deployable weights.

    python -m manatee_tpu.health.train [-o weights.npz] [--steps N]

Training runs in JAX (data-parallel over every visible device via
make_mesh_train_step — the accelerator path the driver dry-runs);
the result is exported as a plain .npz that telemetry.NumpyScorer
loads inside the sitter daemons without importing JAX.
"""

from __future__ import annotations

import argparse

import numpy as np


def train(steps: int = 300, batch: int = 256, lr: float = 5e-2, seed: int = 0):
    if steps < 1:
        raise ValueError("steps must be >= 1")
    import jax

    from manatee_tpu.health.predictor import (
        init_params,
        make_mesh_train_step,
        predict,
        synthetic_batch,
        train_step,
    )

    params = init_params(jax.random.PRNGKey(seed))
    devices = jax.devices()
    mesh = None
    if len(devices) > 1:
        from jax.sharding import Mesh
        # the data axis must divide the batch or device_put rejects the
        # sharding; use the largest device count that does
        usable = max(d for d in range(1, len(devices) + 1)
                     if batch % d == 0)
        if usable > 1:
            mesh = Mesh(np.array(devices[:usable]), axis_names=("data",))

    key = jax.random.PRNGKey(seed + 1)
    if mesh is not None:
        with mesh:
            step, data_sharding, repl = make_mesh_train_step(mesh)
            params = jax.device_put(params, repl)
            for i in range(steps):
                key, sub = jax.random.split(key)
                w, y = synthetic_batch(sub, batch)
                w = jax.device_put(w, data_sharding)
                y = jax.device_put(y, data_sharding)
                params, loss = step(params, w, y, lr)
    else:
        for i in range(steps):
            key, sub = jax.random.split(key)
            w, y = synthetic_batch(sub, batch)
            params, loss = train_step(params, w, y, lr)

    # held-out accuracy
    w, y = synthetic_batch(jax.random.PRNGKey(seed + 999), 2048)
    acc = float(((predict(params, w) > 0.5) == (y > 0.5)).mean())
    return params, float(loss), acc


def export(params, path: str) -> None:
    np.savez(path, **{k: np.asarray(v)
                      for k, v in params._asdict().items()})


def evaluate(weights_path=None, *, n_traces: int = 200, ramp: int = 12,
             healthy_ticks: int = 40, seed: int = 0) -> dict:
    """Operationally meaningful evaluation through the DEPLOYED path:
    feed simulated probe ticks through the same TelemetryRing +
    NumpyScorer the sitter daemons run, and measure

    * detection rate: fraction of degradation traces whose score
      crosses WARN_THRESHOLD before the hard failure at ramp end;
    * lead ticks: how many probe ticks of warning before the hard
      failure (ticks == healthChkInterval, 1 s in production);
    * false positives: healthy-trace ticks scored above threshold.

    Degradation traces ramp latency/timeouts/lag/stalls over *ramp*
    ticks, the same failure signature synthetic_batch trains on; the
    hard failure (reference semantics: healthChkTimeout trips) is
    placed at the end of the ramp.
    """
    from manatee_tpu.health.telemetry import (
        WARN_THRESHOLD,
        NumpyScorer,
        TelemetryRing,
    )

    rng = np.random.default_rng(seed)
    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    leads: list[int] = []
    detected = 0
    fp_ticks = 0
    healthy_scored = 0

    def healthy_tick(ring, lsn):
        ring.add(latency_ms=5 + 25 * rng.random(), timed_out=False,
                 lag_s=0.05 * rng.random(), wal_lsn=lsn,
                 in_recovery=True)

    for _ in range(n_traces):
        ring = TelemetryRing()
        lsn = 0
        for _ in range(healthy_ticks):
            lsn += int(1000 * (1 + rng.random()))
            healthy_tick(ring, lsn)
            if ring.ready():
                s = scorer.score(ring.window_array())
                healthy_scored += 1
                if s is not None and s > WARN_THRESHOLD:
                    fp_ticks += 1
        # degradation: the same signature synthetic_batch trains on,
        # ending in the hard failure at tick `ramp`
        warn_at = None
        for j in range(ramp):
            f = (j + 1) / ramp
            ring.add(
                latency_ms=30 + 970 * f * rng.random(),
                timed_out=rng.random() < 0.6 * f,
                lag_s=10.0 * f * rng.random(),
                wal_lsn=lsn,              # WAL stops advancing
                in_recovery=True)
            if not ring.ready():
                continue   # the deployed path never scores a cold ring
            s = scorer.score(ring.window_array())
            if warn_at is None and s is not None and s > WARN_THRESHOLD:
                warn_at = j
        # lead counts ticks strictly BEFORE the hard failure (which
        # fires on the final ramp tick, index ramp-1)
        if warn_at is not None and warn_at < ramp - 1:
            detected += 1
            leads.append(ramp - 1 - warn_at)

    return {
        "n_traces": n_traces,
        "detection_rate": detected / n_traces,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp_ticks / healthy_scored
                                if healthy_scored else 0.0),
    }


def evaluate_recorded(paths, weights_path=None, *,
                      horizon: int = 8) -> dict:
    """Evaluate the predictor on RECORDED traces — the JSONL files
    PostgresMgr writes when telemetryDump is set (one line per probe
    tick, raw ring inputs), captured from real chaos/integration runs.
    Closes the sim-to-real loop: the synthetic eval above shows what
    the model was taught; this shows how it does on what the deployed
    path actually saw.

    Labels come from the reference's own reactive semantics
    (lib/postgresMgr.js:1550-1646): a hard failure is the first
    timed-out probe after a healthy stretch — exactly the tick the
    healthChkTimeout contract declares the database unhealthy.  A
    useful warning is a score crossing WARN_THRESHOLD strictly before
    that tick; a false positive is a warning with no hard failure
    within *horizon* subsequent ticks.

    Returns {n_traces, n_failures, detected, detection_rate,
    median_lead_ticks, min_lead_ticks, false_positive_rate,
    scored_ticks}.  Traces too short to score, or with no failure and
    no warnings, still count toward scored_ticks/FP accounting.
    """
    import json as _json

    from manatee_tpu.health.telemetry import (
        WARN_THRESHOLD,
        NumpyScorer,
        TelemetryRing,
    )

    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    n_traces = 0
    failures = 0
    detected = 0
    leads: list[int] = []
    scored = 0
    fp = 0

    for path in paths:
        ticks = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    ticks.append(_json.loads(line))
        if not ticks:
            continue
        n_traces += 1
        # replay through the deployed scoring path
        ring = TelemetryRing()
        warns: list[int] = []
        timeouts: list[int] = []
        for i, t in enumerate(ticks):
            ring.add(latency_ms=float(t.get("latency_ms") or 0.0),
                     timed_out=bool(t.get("timed_out")),
                     lag_s=t.get("lag_s"),
                     wal_lsn=t.get("wal_lsn"),
                     in_recovery=bool(t.get("in_recovery")))
            if t.get("timed_out"):
                timeouts.append(i)
            if not ring.ready():
                continue
            s = scorer.score(ring.window_array())
            scored += 1
            if s is not None and s > WARN_THRESHOLD:
                warns.append(i)
        # hard failures: first timeout of each failure episode (a
        # timeout NOT immediately preceded by another timeout)
        hard = [i for i in timeouts
                if i == 0 or (i - 1) not in timeouts]
        failures += len(hard)
        for h in hard:
            early = [w for w in warns if w < h and h - w <= horizon]
            if early:
                detected += 1
                leads.append(h - max(early))
        # false positives: warnings with no hard failure close behind
        for w in warns:
            if not any(0 < h - w <= horizon for h in hard):
                fp += 1

    return {
        "n_traces": n_traces,
        "n_failures": failures,
        "detected": detected,
        "detection_rate": (detected / failures) if failures else None,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp / scored) if scored else 0.0,
        "scored_ticks": scored,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-o", "--out", default=None,
                   help="output .npz (default: packaged weights path)")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=256)
    args = p.parse_args(argv)

    out = args.out
    if out is None:
        from manatee_tpu.health.telemetry import DEFAULT_WEIGHTS
        out = str(DEFAULT_WEIGHTS)

    params, loss, acc = train(steps=args.steps, batch=args.batch)
    export(params, out)
    print("trained %d steps: loss %.4f, held-out acc %.3f -> %s"
          % (args.steps, loss, acc, out))
    ev = evaluate(out)
    print("deployed-path eval: detection %.1f%%, median lead %g ticks "
          "(min %d), healthy-tick FPR %.4f"
          % (100 * ev["detection_rate"], ev["median_lead_ticks"],
             ev["min_lead_ticks"], ev["false_positive_rate"]))


if __name__ == "__main__":
    main()
