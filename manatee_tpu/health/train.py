"""Train the failure-prediction model and export deployable weights.

    python -m manatee_tpu.health.train [-o weights.npz] [--steps N]

Training runs in JAX (data-parallel over every visible device via
make_mesh_train_step — the accelerator path the driver dry-runs);
the result is exported as a plain .npz that telemetry.NumpyScorer
loads inside the sitter daemons without importing JAX.
"""

from __future__ import annotations

import argparse

import numpy as np


def _load_ticks(path) -> list[dict]:
    """One recorded telemetry dump (telemetryDump JSONL) -> tick dicts."""
    import json as _json

    ticks = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                ticks.append(_json.loads(line))
    return ticks


def _episode_spans(ticks) -> list[tuple[int, int]]:
    """Failure episodes: maximal runs of consecutive timed-out ticks.
    The hard failure (reference reactive semantics,
    lib/postgresMgr.js:1550-1646) is each episode's FIRST tick."""
    episodes: list[tuple[int, int]] = []
    for i, t in enumerate(ticks):
        if not t.get("timed_out"):
            continue
        if episodes and i == episodes[-1][1] + 1:
            episodes[-1] = (episodes[-1][0], i)
        else:
            episodes.append((i, i))
    return episodes


def _feed(ring, t) -> None:
    """Replay one recorded tick into the ring EXACTLY as the deployed
    path fed it (pg/manager.py _record_telemetry): failed probes enter
    at the shared latency clamp, however fast the failure was."""
    from manatee_tpu.health.telemetry import FAILED_PROBE_LATENCY_MS

    timed_out = bool(t.get("timed_out"))
    ring.add(latency_ms=(FAILED_PROBE_LATENCY_MS if timed_out
                         else float(t.get("latency_ms") or 0.0)),
             timed_out=timed_out, lag_s=t.get("lag_s"),
             wal_lsn=t.get("wal_lsn"),
             in_recovery=bool(t.get("in_recovery")))


def recorded_windows(paths, *, horizon: int = 8,
                     include_positives: bool = False
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Labeled training windows from recorded telemetry dumps (the
    JSONL files harness runs leave behind), replayed through the
    deployed TelemetryRing with the same episode accounting
    evaluate_recorded uses:

    * label 0: windows on healthy stretches — the chaos-storm negatives
      (restore churn, flapping neighbors) that synthetic data cannot
      model, the main source of real-trace false positives;
    * label 1 (only with *include_positives*): windows within *horizon*
      ticks before a hard failure and not dominated by a previous
      episode.  OFF by default: storm failures are abrupt SIGKILLs
      whose pre-failure windows genuinely look healthy, so these labels
      are noise — measured on held-out traces, mixing them in raised
      the false-positive rate ~5x vs negatives-only (synthetic data
      already supplies the degradation-signature positives).

    Windows inside an episode or its recovery shadow carry no label
    either way and are dropped."""
    from manatee_tpu.health.telemetry import WINDOW, TelemetryRing

    shadow = max(horizon, WINDOW)
    wins: list[np.ndarray] = []
    labels: list[float] = []
    for path in paths:
        ticks = _load_ticks(path)
        if not ticks:
            continue
        episodes = _episode_spans(ticks)
        hard = [start for start, _end in episodes]

        ring = TelemetryRing()
        for i, t in enumerate(ticks):
            _feed(ring, t)
            if not ring.ready():
                continue
            in_zone = any(start - horizon <= i <= end + shadow
                          for start, end in episodes)
            if not in_zone:
                wins.append(ring.window_array().copy())
                labels.append(0.0)
            elif include_positives and \
                    any(0 < h - i <= horizon for h in hard) and \
                    not any(start <= i <= end + shadow
                            for start, end in episodes):
                wins.append(ring.window_array().copy())
                labels.append(1.0)
    if not wins:
        return (np.zeros((0, 0, 0), np.float32),
                np.zeros((0,), np.float32))
    return (np.stack(wins).astype(np.float32),
            np.asarray(labels, np.float32))


def train(steps: int = 300, batch: int = 256, lr: float = 5e-2,
          seed: int = 0, recorded: tuple | None = None,
          recorded_frac: float = 0.03):
    """*recorded*: optional (windows, labels) from recorded_windows —
    up to *recorded_frac* of every batch is drawn from it (sampled with
    replacement), the rest stays synthetic so the degradation signature
    is never diluted away.  0.03 measured best on held-out storm
    seeds: real-trace FP reaches 0 while synthetic detection stays 97%
    and a sparse-cadence no-timeout degradation still scores ~0.99;
    higher fractions suppress the no-timeout degradation signal below
    the warning threshold with no further FP gain."""
    if steps < 1:
        raise ValueError("steps must be >= 1")
    import jax

    from manatee_tpu.health.predictor import (
        init_params,
        make_mesh_train_step,
        predict,
        synthetic_batch,
        train_step,
    )
    import jax.numpy as jnp

    rec_w = rec_y = None
    n_rec = 0
    if recorded is not None and len(recorded[1]):
        rec_w, rec_y = recorded
        # floor of 1: a small --batch must not silently drop the mix
        # the caller explicitly provided
        n_rec = min(max(1, int(batch * recorded_frac)), batch - 1)
    n_syn = batch - n_rec
    rng = np.random.default_rng(seed + 7)

    def make_batch(sub):
        w, y = synthetic_batch(sub, n_syn)
        if n_rec:
            idx = rng.integers(0, len(rec_y), size=n_rec)
            w = jnp.concatenate([w, jnp.asarray(rec_w[idx])])
            y = jnp.concatenate([y, jnp.asarray(rec_y[idx])])
        return w, y

    params = init_params(jax.random.PRNGKey(seed))
    devices = jax.devices()
    mesh = None
    if len(devices) > 1:
        from jax.sharding import Mesh
        # the data axis must divide the batch or device_put rejects the
        # sharding; use the largest device count that does
        usable = max(d for d in range(1, len(devices) + 1)
                     if batch % d == 0)
        if usable > 1:
            mesh = Mesh(np.array(devices[:usable]), axis_names=("data",))

    key = jax.random.PRNGKey(seed + 1)
    if mesh is not None:
        with mesh:
            step, data_sharding, repl = make_mesh_train_step(mesh)
            params = jax.device_put(params, repl)
            for i in range(steps):
                key, sub = jax.random.split(key)
                w, y = make_batch(sub)
                w = jax.device_put(w, data_sharding)
                y = jax.device_put(y, data_sharding)
                params, loss = step(params, w, y, lr)
    else:
        for i in range(steps):
            key, sub = jax.random.split(key)
            w, y = make_batch(sub)
            params, loss = train_step(params, w, y, lr)

    # held-out accuracy
    w, y = synthetic_batch(jax.random.PRNGKey(seed + 999), 2048)
    acc = float(((predict(params, w) > 0.5) == (y > 0.5)).mean())
    return params, float(loss), acc


def export(params, path: str) -> None:
    np.savez(path, **{k: np.asarray(v)
                      for k, v in params._asdict().items()})


def evaluate(weights_path=None, *, n_traces: int = 200, ramp: int = 12,
             healthy_ticks: int = 40, seed: int = 0,
             status_every: int | None = None) -> dict:
    """Operationally meaningful evaluation through the DEPLOYED path:
    feed simulated probe ticks through the same TelemetryRing +
    NumpyScorer the sitter daemons run, and measure

    * detection rate: fraction of degradation traces whose score
      crosses WARN_THRESHOLD before the hard failure at ramp end;
    * lead ticks: how many probe ticks of warning before the hard
      failure (ticks == healthChkInterval, 1 s in production);
    * false positives: healthy-trace ticks scored above threshold.

    Degradation traces ramp latency/timeouts/lag/stalls over *ramp*
    ticks, the same failure signature synthetic_batch trains on; the
    hard failure (reference semantics: healthChkTimeout trips) is
    placed at the end of the ramp.  *status_every* mirrors the
    manager's cadence (pg/manager.py _STATUS_EVERY): lag/WAL reach the
    ring only on every Nth probe, the other ticks carry them forward —
    scoring must work on what the deployed path actually sees.
    """
    from manatee_tpu.health.telemetry import (
        STATUS_EVERY,
        WARN_THRESHOLD,
        NumpyScorer,
        TelemetryRing,
    )

    if status_every is None:
        status_every = STATUS_EVERY
    rng = np.random.default_rng(seed)
    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    leads: list[int] = []
    detected = 0
    fp_ticks = 0
    healthy_scored = 0

    for _ in range(n_traces):
        ring = TelemetryRing()
        lsn = 0
        tick_no = 0

        def add(ring, *, latency_ms, timed_out, lag_s, wal_lsn,
                in_recovery=True):
            nonlocal tick_no
            tick_no += 1
            # the manager attaches the status op only to every Nth
            # SUCCESSFUL probe (pg/manager.py _health_loop: `if ok and
            # tick % _STATUS_EVERY == 0`) — a failed probe never
            # observes lag/wal
            if not timed_out and tick_no % status_every == 0:
                ring.add(latency_ms=latency_ms, timed_out=timed_out,
                         lag_s=lag_s, wal_lsn=wal_lsn,
                         in_recovery=in_recovery)
            else:   # no status this tick: ring carries lag/wal forward
                ring.add(latency_ms=latency_ms, timed_out=timed_out,
                         lag_s=None, wal_lsn=None,
                         in_recovery=in_recovery)

        for _ in range(healthy_ticks):
            lsn += int(1000 * (1 + rng.random()))
            add(ring, latency_ms=5 + 25 * rng.random(),
                timed_out=False, lag_s=0.05 * rng.random(), wal_lsn=lsn)
            if ring.ready():
                s = scorer.score(ring.window_array())
                healthy_scored += 1
                if s is not None and s > WARN_THRESHOLD:
                    fp_ticks += 1
        # degradation: the same signature synthetic_batch trains on,
        # ending in the hard failure at tick `ramp`
        warn_at = None
        for j in range(ramp):
            f = (j + 1) / ramp
            add(ring,
                latency_ms=30 + 970 * f * rng.random(),
                timed_out=rng.random() < 0.6 * f,
                lag_s=10.0 * f * rng.random(),
                wal_lsn=lsn)              # WAL stops advancing
            if not ring.ready():
                continue   # the deployed path never scores a cold ring
            s = scorer.score(ring.window_array())
            if warn_at is None and s is not None and s > WARN_THRESHOLD:
                warn_at = j
        # lead counts ticks strictly BEFORE the hard failure (which
        # fires on the final ramp tick, index ramp-1)
        if warn_at is not None and warn_at < ramp - 1:
            detected += 1
            leads.append(ramp - 1 - warn_at)

    return {
        "n_traces": n_traces,
        "detection_rate": detected / n_traces,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp_ticks / healthy_scored
                                if healthy_scored else 0.0),
    }


def evaluate_recorded(paths, weights_path=None, *,
                      horizon: int = 8) -> dict:
    """Evaluate the predictor on RECORDED traces — the JSONL files
    PostgresMgr writes when telemetryDump is set (one line per probe
    tick, raw ring inputs), captured from real chaos/integration runs.
    Closes the sim-to-real loop: the synthetic eval above shows what
    the model was taught; this shows how it does on what the deployed
    path actually saw.

    Labels come from the reference's own reactive semantics
    (lib/postgresMgr.js:1550-1646): a hard failure is the first
    timed-out probe after a healthy stretch — exactly the tick the
    healthChkTimeout contract declares the database unhealthy.  A
    useful warning is a score crossing WARN_THRESHOLD strictly before
    that tick, scored on a window not already dominated by a previous
    episode.  False positives are counted ONLY on healthy stretches:
    ticks inside a failure episode (consecutive timeouts), within
    *horizon* before a hard failure (that's the warning we want), or
    within max(*horizon*, WINDOW) after an episode ends (the ring
    still holds the outage for WINDOW ticks) are excluded from both
    the FP numerator and denominator — an outage is one failure, not
    twenty false alarms.

    Replay is bit-faithful to the deployed path: the ring is fed the
    same latency substitution PostgresMgr applies — both sites share
    telemetry.FAILED_PROBE_LATENCY_MS, so a refused connection that
    fails in ~1 ms replays exactly as the deployed path saw it.

    Returns {n_traces, n_failures, detected, detection_rate,
    median_lead_ticks, min_lead_ticks, false_positive_rate,
    scored_ticks, healthy_ticks, unscoreable_failures}.  Episodes that
    begin before the ring was ever scoreable (database still booting
    at trace start) are unscoreable_failures — reported, not counted
    as misses.  Traces too short to score, or with no failure and no
    warnings, still count toward FP accounting.
    """
    from manatee_tpu.health.telemetry import (
        WARN_THRESHOLD,
        WINDOW,
        NumpyScorer,
        TelemetryRing,
    )

    # the ring still holds an ended episode's ticks for WINDOW ticks
    # after it, so warnings there are the outage draining out of the
    # window, not predictions — excluded regardless of how short a
    # lead-time horizon the caller asked for
    shadow = max(horizon, WINDOW)

    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    n_traces = 0
    failures = 0
    detected = 0
    leads: list[int] = []
    scored = 0
    healthy_scored = 0
    fp = 0
    unscoreable = 0

    for path in paths:
        ticks = _load_ticks(path)
        if not ticks:
            continue
        n_traces += 1
        # replay through the deployed scoring path
        ring = TelemetryRing()
        warns: list[int] = []
        scored_at: list[int] = []
        for i, t in enumerate(ticks):
            _feed(ring, t)
            if not ring.ready():
                continue
            s = scorer.score(ring.window_array())
            scored += 1
            scored_at.append(i)
            if s is not None and s > WARN_THRESHOLD:
                warns.append(i)
        episodes = _episode_spans(ticks)
        # a failure is assessable only if at least one scored tick
        # precedes it — every real trace begins with timed-out probes
        # while the database is still booting, and no predictor can
        # warn before the ring has ever been scoreable.  Those are
        # reported, not counted as misses.
        first_scored = scored_at[0] if scored_at else len(ticks)
        hard = [start for start, _end in episodes
                if start > first_scored]
        unscoreable += sum(1 for start, _end in episodes
                           if start <= first_scored)
        failures += len(hard)

        def polluted(i: int) -> bool:
            """Tick *i*'s window is dominated by an episode already in
            progress or just ended — a warning there observes THAT
            outage; crediting it as a prediction of the next one would
            inflate detection whenever a flapping database produces
            episodes within *horizon* of each other."""
            return any(start <= i <= end + shadow
                       for start, end in episodes)

        for h in hard:
            early = [w for w in warns
                     if w < h and h - w <= horizon and not polluted(w)]
            if early:
                detected += 1
                leads.append(h - max(early))

        def on_healthy_stretch(i: int) -> bool:
            for start, end in episodes:
                if start - horizon <= i <= end + shadow:
                    return False
            return True
        healthy_scored += sum(1 for i in scored_at
                              if on_healthy_stretch(i))
        fp += sum(1 for w in warns if on_healthy_stretch(w))

    return {
        "n_traces": n_traces,
        "n_failures": failures,
        "detected": detected,
        "detection_rate": (detected / failures) if failures else None,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp / healthy_scored
                                if healthy_scored else 0.0),
        "scored_ticks": scored,
        "healthy_ticks": healthy_scored,
        "unscoreable_failures": unscoreable,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-o", "--out", default=None,
                   help="output .npz (default: packaged weights path)")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--recorded", nargs="+", metavar="JSONL",
                   help="skip training; evaluate the packaged weights "
                        "(or -o) on recorded telemetry dumps and print "
                        "one JSON result line")
    p.add_argument("--horizon", type=int, default=8,
                   help="ticks of lead counted as a useful warning "
                        "(with --recorded)")
    p.add_argument("--mix-recorded", nargs="+", metavar="JSONL",
                   dest="mix_recorded",
                   help="mix healthy-stretch windows extracted from "
                        "recorded telemetry dumps into training — "
                        "teaches the model the storm negatives "
                        "synthetic data cannot model")
    p.add_argument("--recorded-frac", type=float, default=0.03,
                   dest="recorded_frac",
                   help="fraction of each batch drawn from the "
                        "recorded mix (default 0.03 — measured best: "
                        "held-out storm FP reaches 0 while synthetic "
                        "detection stays 97%%)")
    args = p.parse_args(argv)

    if args.recorded:
        import json as _json
        ev = evaluate_recorded(args.recorded, args.out,
                               horizon=args.horizon)
        print(_json.dumps(ev))
        return

    out = args.out
    if out is None:
        from manatee_tpu.health.telemetry import DEFAULT_WEIGHTS
        out = str(DEFAULT_WEIGHTS)

    recorded = None
    if args.mix_recorded:
        recorded = recorded_windows(args.mix_recorded,
                                    horizon=args.horizon)
        print("recorded mix: %d windows (%d positive) from %d dumps"
              % (len(recorded[1]), int(recorded[1].sum()),
                 len(args.mix_recorded)))

    params, loss, acc = train(steps=args.steps, batch=args.batch,
                              recorded=recorded,
                              recorded_frac=args.recorded_frac)
    export(params, out)
    print("trained %d steps: loss %.4f, held-out acc %.3f -> %s"
          % (args.steps, loss, acc, out))
    ev = evaluate(out)
    print("deployed-path eval: detection %.1f%%, median lead %g ticks "
          "(min %d), healthy-tick FPR %.4f"
          % (100 * ev["detection_rate"], ev["median_lead_ticks"],
             ev["min_lead_ticks"], ev["false_positive_rate"]))


if __name__ == "__main__":
    main()
