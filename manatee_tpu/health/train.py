"""Train the failure-prediction model and export deployable weights.

    python -m manatee_tpu.health.train [-o weights.npz] [--steps N]

Training runs in JAX (data-parallel over every visible device via
make_mesh_train_step — the accelerator path the driver dry-runs);
the result is exported as a plain .npz that telemetry.NumpyScorer
loads inside the sitter daemons without importing JAX.
"""

from __future__ import annotations

import argparse

import numpy as np


def train(steps: int = 300, batch: int = 256, lr: float = 5e-2, seed: int = 0):
    if steps < 1:
        raise ValueError("steps must be >= 1")
    import jax

    from manatee_tpu.health.predictor import (
        init_params,
        make_mesh_train_step,
        predict,
        synthetic_batch,
        train_step,
    )

    params = init_params(jax.random.PRNGKey(seed))
    devices = jax.devices()
    mesh = None
    if len(devices) > 1:
        from jax.sharding import Mesh
        # the data axis must divide the batch or device_put rejects the
        # sharding; use the largest device count that does
        usable = max(d for d in range(1, len(devices) + 1)
                     if batch % d == 0)
        if usable > 1:
            mesh = Mesh(np.array(devices[:usable]), axis_names=("data",))

    key = jax.random.PRNGKey(seed + 1)
    if mesh is not None:
        with mesh:
            step, data_sharding, repl = make_mesh_train_step(mesh)
            params = jax.device_put(params, repl)
            for i in range(steps):
                key, sub = jax.random.split(key)
                w, y = synthetic_batch(sub, batch)
                w = jax.device_put(w, data_sharding)
                y = jax.device_put(y, data_sharding)
                params, loss = step(params, w, y, lr)
    else:
        for i in range(steps):
            key, sub = jax.random.split(key)
            w, y = synthetic_batch(sub, batch)
            params, loss = train_step(params, w, y, lr)

    # held-out accuracy
    w, y = synthetic_batch(jax.random.PRNGKey(seed + 999), 2048)
    acc = float(((predict(params, w) > 0.5) == (y > 0.5)).mean())
    return params, float(loss), acc


def export(params, path: str) -> None:
    np.savez(path, **{k: np.asarray(v)
                      for k, v in params._asdict().items()})


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-o", "--out", default=None,
                   help="output .npz (default: packaged weights path)")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=256)
    args = p.parse_args(argv)

    out = args.out
    if out is None:
        from manatee_tpu.health.telemetry import DEFAULT_WEIGHTS
        out = str(DEFAULT_WEIGHTS)

    params, loss, acc = train(steps=args.steps, batch=args.batch)
    export(params, out)
    print("trained %d steps: loss %.4f, held-out acc %.3f -> %s"
          % (args.steps, loss, acc, out))


if __name__ == "__main__":
    main()
