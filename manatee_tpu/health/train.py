"""Train the failure-prediction model and export deployable weights.

    python -m manatee_tpu.health.train [-o weights.npz] [--steps N]

Training runs in JAX (data-parallel over every visible device via
make_mesh_train_step — the accelerator path the driver dry-runs);
the result is exported as a plain .npz that telemetry.NumpyScorer
loads inside the sitter daemons without importing JAX.
"""

from __future__ import annotations

import argparse

import numpy as np


def train(steps: int = 300, batch: int = 256, lr: float = 5e-2, seed: int = 0):
    if steps < 1:
        raise ValueError("steps must be >= 1")
    import jax

    from manatee_tpu.health.predictor import (
        init_params,
        make_mesh_train_step,
        predict,
        synthetic_batch,
        train_step,
    )

    params = init_params(jax.random.PRNGKey(seed))
    devices = jax.devices()
    mesh = None
    if len(devices) > 1:
        from jax.sharding import Mesh
        # the data axis must divide the batch or device_put rejects the
        # sharding; use the largest device count that does
        usable = max(d for d in range(1, len(devices) + 1)
                     if batch % d == 0)
        if usable > 1:
            mesh = Mesh(np.array(devices[:usable]), axis_names=("data",))

    key = jax.random.PRNGKey(seed + 1)
    if mesh is not None:
        with mesh:
            step, data_sharding, repl = make_mesh_train_step(mesh)
            params = jax.device_put(params, repl)
            for i in range(steps):
                key, sub = jax.random.split(key)
                w, y = synthetic_batch(sub, batch)
                w = jax.device_put(w, data_sharding)
                y = jax.device_put(y, data_sharding)
                params, loss = step(params, w, y, lr)
    else:
        for i in range(steps):
            key, sub = jax.random.split(key)
            w, y = synthetic_batch(sub, batch)
            params, loss = train_step(params, w, y, lr)

    # held-out accuracy
    w, y = synthetic_batch(jax.random.PRNGKey(seed + 999), 2048)
    acc = float(((predict(params, w) > 0.5) == (y > 0.5)).mean())
    return params, float(loss), acc


def export(params, path: str) -> None:
    np.savez(path, **{k: np.asarray(v)
                      for k, v in params._asdict().items()})


def evaluate(weights_path=None, *, n_traces: int = 200, ramp: int = 12,
             healthy_ticks: int = 40, seed: int = 0) -> dict:
    """Operationally meaningful evaluation through the DEPLOYED path:
    feed simulated probe ticks through the same TelemetryRing +
    NumpyScorer the sitter daemons run, and measure

    * detection rate: fraction of degradation traces whose score
      crosses WARN_THRESHOLD before the hard failure at ramp end;
    * lead ticks: how many probe ticks of warning before the hard
      failure (ticks == healthChkInterval, 1 s in production);
    * false positives: healthy-trace ticks scored above threshold.

    Degradation traces ramp latency/timeouts/lag/stalls over *ramp*
    ticks, the same failure signature synthetic_batch trains on; the
    hard failure (reference semantics: healthChkTimeout trips) is
    placed at the end of the ramp.
    """
    from manatee_tpu.health.telemetry import (
        WARN_THRESHOLD,
        NumpyScorer,
        TelemetryRing,
    )

    rng = np.random.default_rng(seed)
    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    leads: list[int] = []
    detected = 0
    fp_ticks = 0
    healthy_scored = 0

    def healthy_tick(ring, lsn):
        ring.add(latency_ms=5 + 25 * rng.random(), timed_out=False,
                 lag_s=0.05 * rng.random(), wal_lsn=lsn,
                 in_recovery=True)

    for _ in range(n_traces):
        ring = TelemetryRing()
        lsn = 0
        for _ in range(healthy_ticks):
            lsn += int(1000 * (1 + rng.random()))
            healthy_tick(ring, lsn)
            if ring.ready():
                s = scorer.score(ring.window_array())
                healthy_scored += 1
                if s is not None and s > WARN_THRESHOLD:
                    fp_ticks += 1
        # degradation: the same signature synthetic_batch trains on,
        # ending in the hard failure at tick `ramp`
        warn_at = None
        for j in range(ramp):
            f = (j + 1) / ramp
            ring.add(
                latency_ms=30 + 970 * f * rng.random(),
                timed_out=rng.random() < 0.6 * f,
                lag_s=10.0 * f * rng.random(),
                wal_lsn=lsn,              # WAL stops advancing
                in_recovery=True)
            if not ring.ready():
                continue   # the deployed path never scores a cold ring
            s = scorer.score(ring.window_array())
            if warn_at is None and s is not None and s > WARN_THRESHOLD:
                warn_at = j
        # lead counts ticks strictly BEFORE the hard failure (which
        # fires on the final ramp tick, index ramp-1)
        if warn_at is not None and warn_at < ramp - 1:
            detected += 1
            leads.append(ramp - 1 - warn_at)

    return {
        "n_traces": n_traces,
        "detection_rate": detected / n_traces,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp_ticks / healthy_scored
                                if healthy_scored else 0.0),
    }


def evaluate_recorded(paths, weights_path=None, *,
                      horizon: int = 8) -> dict:
    """Evaluate the predictor on RECORDED traces — the JSONL files
    PostgresMgr writes when telemetryDump is set (one line per probe
    tick, raw ring inputs), captured from real chaos/integration runs.
    Closes the sim-to-real loop: the synthetic eval above shows what
    the model was taught; this shows how it does on what the deployed
    path actually saw.

    Labels come from the reference's own reactive semantics
    (lib/postgresMgr.js:1550-1646): a hard failure is the first
    timed-out probe after a healthy stretch — exactly the tick the
    healthChkTimeout contract declares the database unhealthy.  A
    useful warning is a score crossing WARN_THRESHOLD strictly before
    that tick, scored on a window not already dominated by a previous
    episode.  False positives are counted ONLY on healthy stretches:
    ticks inside a failure episode (consecutive timeouts), within
    *horizon* before a hard failure (that's the warning we want), or
    within max(*horizon*, WINDOW) after an episode ends (the ring
    still holds the outage for WINDOW ticks) are excluded from both
    the FP numerator and denominator — an outage is one failure, not
    twenty false alarms.

    Replay is bit-faithful to the deployed path: the ring is fed the
    same latency substitution PostgresMgr applies — both sites share
    telemetry.FAILED_PROBE_LATENCY_MS, so a refused connection that
    fails in ~1 ms replays exactly as the deployed path saw it.

    Returns {n_traces, n_failures, detected, detection_rate,
    median_lead_ticks, min_lead_ticks, false_positive_rate,
    scored_ticks, healthy_ticks, unscoreable_failures}.  Episodes that
    begin before the ring was ever scoreable (database still booting
    at trace start) are unscoreable_failures — reported, not counted
    as misses.  Traces too short to score, or with no failure and no
    warnings, still count toward FP accounting.
    """
    import json as _json

    from manatee_tpu.health.telemetry import (
        FAILED_PROBE_LATENCY_MS,
        WARN_THRESHOLD,
        WINDOW,
        NumpyScorer,
        TelemetryRing,
    )

    # the ring still holds an ended episode's ticks for WINDOW ticks
    # after it, so warnings there are the outage draining out of the
    # window, not predictions — excluded regardless of how short a
    # lead-time horizon the caller asked for
    shadow = max(horizon, WINDOW)

    scorer = NumpyScorer(weights_path)
    if not scorer.available:
        raise RuntimeError("no usable weights at %r" % (weights_path,))

    n_traces = 0
    failures = 0
    detected = 0
    leads: list[int] = []
    scored = 0
    healthy_scored = 0
    fp = 0
    unscoreable = 0

    for path in paths:
        ticks = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    ticks.append(_json.loads(line))
        if not ticks:
            continue
        n_traces += 1
        # replay through the deployed scoring path
        ring = TelemetryRing()
        warns: list[int] = []
        scored_at: list[int] = []
        timeouts = [i for i, t in enumerate(ticks) if t.get("timed_out")]
        for i, t in enumerate(ticks):
            timed_out = bool(t.get("timed_out"))
            # deployed-path substitution (pg/manager.py
            # _record_telemetry): failed probes enter the ring at the
            # shared clamp, however fast the failure itself was
            lat = (FAILED_PROBE_LATENCY_MS if timed_out
                   else float(t.get("latency_ms") or 0.0))
            ring.add(latency_ms=lat, timed_out=timed_out,
                     lag_s=t.get("lag_s"),
                     wal_lsn=t.get("wal_lsn"),
                     in_recovery=bool(t.get("in_recovery")))
            if not ring.ready():
                continue
            s = scorer.score(ring.window_array())
            scored += 1
            scored_at.append(i)
            if s is not None and s > WARN_THRESHOLD:
                warns.append(i)
        # failure episodes: maximal runs of consecutive timeouts; the
        # hard failure is each episode's FIRST tick
        episodes: list[tuple[int, int]] = []
        for i in timeouts:
            if episodes and i == episodes[-1][1] + 1:
                episodes[-1] = (episodes[-1][0], i)
            else:
                episodes.append((i, i))
        # a failure is assessable only if at least one scored tick
        # precedes it — every real trace begins with timed-out probes
        # while the database is still booting, and no predictor can
        # warn before the ring has ever been scoreable.  Those are
        # reported, not counted as misses.
        first_scored = scored_at[0] if scored_at else len(ticks)
        hard = [start for start, _end in episodes
                if start > first_scored]
        unscoreable += sum(1 for start, _end in episodes
                           if start <= first_scored)
        failures += len(hard)

        def polluted(i: int) -> bool:
            """Tick *i*'s window is dominated by an episode already in
            progress or just ended — a warning there observes THAT
            outage; crediting it as a prediction of the next one would
            inflate detection whenever a flapping database produces
            episodes within *horizon* of each other."""
            return any(start <= i <= end + shadow
                       for start, end in episodes)

        for h in hard:
            early = [w for w in warns
                     if w < h and h - w <= horizon and not polluted(w)]
            if early:
                detected += 1
                leads.append(h - max(early))

        def on_healthy_stretch(i: int) -> bool:
            for start, end in episodes:
                if start - horizon <= i <= end + shadow:
                    return False
            return True
        healthy_scored += sum(1 for i in scored_at
                              if on_healthy_stretch(i))
        fp += sum(1 for w in warns if on_healthy_stretch(w))

    return {
        "n_traces": n_traces,
        "n_failures": failures,
        "detected": detected,
        "detection_rate": (detected / failures) if failures else None,
        "median_lead_ticks": float(np.median(leads)) if leads else 0.0,
        "min_lead_ticks": min(leads) if leads else 0,
        "false_positive_rate": (fp / healthy_scored
                                if healthy_scored else 0.0),
        "scored_ticks": scored,
        "healthy_ticks": healthy_scored,
        "unscoreable_failures": unscoreable,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-o", "--out", default=None,
                   help="output .npz (default: packaged weights path)")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--recorded", nargs="+", metavar="JSONL",
                   help="skip training; evaluate the packaged weights "
                        "(or -o) on recorded telemetry dumps and print "
                        "one JSON result line")
    p.add_argument("--horizon", type=int, default=8,
                   help="ticks of lead counted as a useful warning "
                        "(with --recorded)")
    args = p.parse_args(argv)

    if args.recorded:
        import json as _json
        ev = evaluate_recorded(args.recorded, args.out,
                               horizon=args.horizon)
        print(_json.dumps(ev))
        return

    out = args.out
    if out is None:
        from manatee_tpu.health.telemetry import DEFAULT_WEIGHTS
        out = str(DEFAULT_WEIGHTS)

    params, loss, acc = train(steps=args.steps, batch=args.batch)
    export(params, out)
    print("trained %d steps: loss %.4f, held-out acc %.3f -> %s"
          % (args.steps, loss, acc, out))
    ev = evaluate(out)
    print("deployed-path eval: detection %.1f%%, median lead %g ticks "
          "(min %d), healthy-tick FPR %.4f"
          % (100 * ev["detection_rate"], ev["median_lead_ticks"],
             ev["min_lead_ticks"], ev["false_positive_rate"]))


if __name__ == "__main__":
    main()
