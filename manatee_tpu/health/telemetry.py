"""Health-probe telemetry collection and in-daemon scoring.

The accelerator side (predictor.py) trains the failure-prediction MLP in
JAX; the control plane must not pay a JAX import (seconds of startup and
hundreds of MB per sitter) to score one 16x5 window per second, so
inference here is a pure-numpy forward pass over exported weights — the
standard train-on-accelerator / deploy-to-edge split.

Feature vector per probe tick (normalized to ~[0, 1]):

  latency_ms  probe round-trip, /1000 clipped at 1 (1s+ latency == 1.0)
  timed_out   1.0 if the probe timed out / failed outright
  lag_s       standby replay lag, /10 clipped (10s+ lag == 1.0)
  wal_stall   1 - wal_advance: 1.0 when the WAL made no progress this
              tick while connected to an upstream (stalled replication),
              0.0 for a healthy advancing WAL (primaries with no write
              load report 0 — idle is not stall; see add())
  reconnects  healthy<->unhealthy flaps in the window, /4 clipped

The reference's reactive semantics (lib/postgresMgr.js:1550-1646: probe
every healthChkInterval, declare unhealthy on healthChkTimeout) are kept
verbatim in PostgresMgr; this model only ADDS an early-warning score
surfaced via GET /state and `manatee-adm pg-status` warnings.
"""

from __future__ import annotations

import collections
from pathlib import Path

import numpy as np

# Model geometry lives HERE (the JAX-free module): predictor.py imports
# these, never the other way around, so daemons and operator tooling can
# collect/score telemetry without paying a JAX import.
N_FEATURES = 5     # latency_ms, timed_out, lag_s, wal_stall, reconnects
WINDOW = 16        # probe ticks per scoring window

# The manager attaches the (potentially multi-query) status op to every
# Nth successful health probe; the ring carries lag/WAL observations
# across the probe-only ticks in between.  Shared by the deployed loop
# (pg/manager.py), synthetic training data (predictor.synthetic_batch
# masks to this cadence), and the deployed-path eval (health/train.py).
STATUS_EVERY = 3

# A failed probe enters the ring at this latency regardless of how fast
# the failure itself was — a refused connection fails in ~1 ms but must
# not look FAST to the model.  Shared by the deployed path
# (pg/manager.py _record_telemetry) and the offline replay
# (health/train.py evaluate_recorded) so they cannot diverge.
FAILED_PROBE_LATENCY_MS = 1000.0

DEFAULT_WEIGHTS = Path(__file__).parent / "weights.npz"
WARN_THRESHOLD = 0.8


def normalize_tick(*, latency_ms: float, timed_out: bool, lag_s: float,
                   wal_stalled: bool, reconnects: int) -> list[float]:
    return [
        min(max(latency_ms, 0.0) / 1000.0, 1.0),
        1.0 if timed_out else 0.0,
        min(max(lag_s, 0.0) / 10.0, 1.0),
        1.0 if wal_stalled else 0.0,
        min(max(reconnects, 0) / 4.0, 1.0),
    ]


class TelemetryRing:
    """Last-WINDOW probe ticks for one database, oldest first."""

    def __init__(self, window: int = WINDOW):
        self.window = window
        self._ticks: collections.deque[list[float]] = \
            collections.deque(maxlen=window)
        self._flaps: collections.deque[int] = collections.deque(maxlen=window)
        self._last_wal: int | None = None
        self._last_ok: bool | None = None
        self._last_lag = 0.0
        self._last_stalled = False

    def add(self, *, latency_ms: float, timed_out: bool,
            lag_s: float | None, wal_lsn: int | None,
            in_recovery: bool) -> None:
        ok = not timed_out
        flap = 1 if (self._last_ok is not None
                     and ok != self._last_ok) else 0
        self._last_ok = ok
        self._flaps.append(flap)
        if lag_s is None and wal_lsn is None:
            # no status observation this tick (the manager piggybacks
            # the status op on a subset of probes; or the query failed):
            # UNKNOWN must not read as healthy — carry the last
            # observed lag/stall forward, staleness bounded by the
            # status cadence
            lag = self._last_lag
            stalled = self._last_stalled
        else:
            # partial observations stay partial: an unknown HALF must
            # not reset the carried other half to healthy
            if lag_s is not None:
                lag = lag_s
            elif in_recovery:
                lag = self._last_lag   # standby, lag unknown: carry
            else:
                lag = 0.0              # a primary has no replay lag
            if wal_lsn is not None:
                # WAL stall: a standby whose WAL is not advancing WHILE
                # lag is accumulating (pending or severed replication).
                # A quiescent cluster's static WAL with zero lag is
                # idle, not stalled.
                stalled = bool(in_recovery
                               and self._last_wal is not None
                               and wal_lsn <= self._last_wal
                               and lag > 1.0)
                self._last_wal = wal_lsn
            else:
                stalled = self._last_stalled   # can't assess w/o WAL
            self._last_lag = lag
            self._last_stalled = stalled
        self._ticks.append(normalize_tick(
            latency_ms=latency_ms, timed_out=timed_out,
            lag_s=lag, wal_stalled=stalled,
            reconnects=sum(self._flaps)))

    def ready(self) -> bool:
        return len(self._ticks) >= self.window // 2

    def window_array(self) -> np.ndarray:
        """[WINDOW, N_FEATURES], zero-padded at the OLD end."""
        out = np.zeros((self.window, N_FEATURES), np.float32)
        ticks = list(self._ticks)
        if ticks:
            out[-len(ticks):] = np.asarray(ticks, np.float32)
        return out

    def last_tick(self) -> list[float] | None:
        return list(self._ticks[-1]) if self._ticks else None


class NumpyScorer:
    """Forward pass of predictor.HealthModel in numpy.

    Weights come from an .npz exported by
    ``python -m manatee_tpu.health.train`` (keys w1,b1,w2,b2,w3,b3).
    Missing/corrupt weights disable scoring (score() -> None) rather
    than degrading the control plane.
    """

    def __init__(self, weights_path: str | Path | None = None):
        path = Path(weights_path or DEFAULT_WEIGHTS)
        self._params: dict[str, np.ndarray] | None = None
        try:
            with np.load(path) as z:
                self._params = {k: z[k].astype(np.float32)
                                for k in ("w1", "b1", "w2", "b2",
                                          "w3", "b3")}
        except Exception:
            # missing/truncated/corrupt weights (incl. BadZipFile) must
            # disable scoring, never take the control plane down
            self._params = None

    @property
    def available(self) -> bool:
        return self._params is not None

    def score(self, window: np.ndarray) -> float | None:
        """Failure probability for one [WINDOW, N_FEATURES] window."""
        p = self._params
        if p is None:
            return None
        x = window.reshape(1, -1).astype(np.float32)
        h = np.maximum(x @ p["w1"] + p["b1"], 0.0)
        h = np.maximum(h @ p["w2"] + p["b2"], 0.0)
        logit = float((h @ p["w3"] + p["b3"])[0, 0])
        return 1.0 / (1.0 + np.exp(-logit))
