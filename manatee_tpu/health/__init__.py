"""Predictive health scoring (JAX).

The reference's failure detection is purely reactive: a 1 s
``select current_time`` probe with a 5 s timeout
(lib/postgresMgr.js:1550-1646) and coordination-session expiry.  This
optional subsystem adds a learned early-warning score over health-probe
telemetry windows (latencies, timeout counts, replication lag) so
operators can be alerted before a peer trips the hard thresholds.  It is
the only numerical workload in this control plane and the target of the
driver's accelerator entry points (__graft_entry__.py).
"""

from manatee_tpu.health.predictor import (
    HealthModel,
    init_params,
    predict,
    train_step,
    make_mesh_train_step,
)

__all__ = [
    "HealthModel",
    "init_params",
    "predict",
    "train_step",
    "make_mesh_train_step",
]
