"""Predictive health scoring.

The reference's failure detection is purely reactive: a 1 s
``select current_time`` probe with a 5 s timeout
(lib/postgresMgr.js:1550-1646) and coordination-session expiry.  This
subsystem adds a learned early-warning score over health-probe telemetry
windows (latencies, timeout counts, replication lag, WAL stalls, flaps)
so operators are alerted before a peer trips the hard thresholds.  It is
the only numerical workload in this control plane and the target of the
driver's accelerator entry points (__graft_entry__.py).

Split: training/prediction in JAX (predictor.py, health.train);
in-daemon collection + inference in numpy (telemetry.py).  The predictor
exports below are LAZY so that importing the control plane (which uses
only telemetry) never pays a JAX import.
"""

_PREDICTOR_EXPORTS = {
    "HealthModel", "init_params", "predict", "train_step",
    "make_mesh_train_step", "synthetic_batch",
}

__all__ = sorted(_PREDICTOR_EXPORTS | {
    "TelemetryRing", "NumpyScorer", "normalize_tick",
    "N_FEATURES", "WINDOW", "WARN_THRESHOLD",
})

from manatee_tpu.health.telemetry import (  # noqa: E402
    N_FEATURES,
    WINDOW,
    WARN_THRESHOLD,
    NumpyScorer,
    TelemetryRing,
    normalize_tick,
)


def __getattr__(name: str):
    if name in _PREDICTOR_EXPORTS:
        from manatee_tpu.health import predictor
        return getattr(predictor, name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
