"""Peer-failure early-warning model.

A small MLP scoring a window of health-probe telemetry per peer:
features per probe tick are [latency_ms, timed_out, replication_lag_s,
wal_stall, reconnects] as produced by telemetry.normalize_tick (note
wal_stall polarity: 1.0 = WAL stalled while lag accumulates = BAD);
a window of W ticks is scored to a failure probability.  Everything is
jittable, static-shaped, and batched so it maps onto accelerator matrix
units; the training step is data-parallel over a ``jax.sharding.Mesh``
with replicated parameters and sharded batches (gradient psum inserted
by the partitioner).  Inference inside the daemons is numpy
(telemetry.NumpyScorer) over weights exported by health.train.

This is deliberately small: the control plane's job is HA PostgreSQL,
and this model augments (never replaces) the reference's reactive
detection semantics.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from manatee_tpu.health.telemetry import N_FEATURES, STATUS_EVERY, WINDOW

HIDDEN = 32


class HealthModel(NamedTuple):
    w1: jax.Array   # [WINDOW * N_FEATURES, HIDDEN]
    b1: jax.Array   # [HIDDEN]
    w2: jax.Array   # [HIDDEN, HIDDEN]
    b2: jax.Array   # [HIDDEN]
    w3: jax.Array   # [HIDDEN, 1]
    b3: jax.Array   # [1]


def init_params(key: jax.Array) -> HealthModel:
    k1, k2, k3 = jax.random.split(key, 3)
    d_in = WINDOW * N_FEATURES
    s1 = (2.0 / d_in) ** 0.5
    s2 = (2.0 / HIDDEN) ** 0.5
    return HealthModel(
        w1=jax.random.normal(k1, (d_in, HIDDEN), jnp.float32) * s1,
        b1=jnp.zeros((HIDDEN,), jnp.float32),
        w2=jax.random.normal(k2, (HIDDEN, HIDDEN), jnp.float32) * s2,
        b2=jnp.zeros((HIDDEN,), jnp.float32),
        w3=jax.random.normal(k3, (HIDDEN, 1), jnp.float32) * s2,
        b3=jnp.zeros((1,), jnp.float32),
    )


def _logits(params: HealthModel, windows: jax.Array) -> jax.Array:
    """windows: [batch, WINDOW, N_FEATURES] -> [batch] logits."""
    x = windows.reshape((windows.shape[0], WINDOW * N_FEATURES))
    h = jax.nn.relu(x @ params.w1 + params.b1)
    h = jax.nn.relu(h @ params.w2 + params.b2)
    return (h @ params.w3 + params.b3)[:, 0]


@jax.jit
def predict(params: HealthModel, windows: jax.Array) -> jax.Array:
    """Failure probability per window, [batch]."""
    return jax.nn.sigmoid(_logits(params, windows))


def _loss(params: HealthModel, windows: jax.Array,
          labels: jax.Array) -> jax.Array:
    logits = _logits(params, windows)
    # numerically-stable binary cross-entropy
    return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


@jax.jit
def train_step(params: HealthModel, windows: jax.Array,
               labels: jax.Array, lr: float = 1e-2
               ) -> tuple[HealthModel, jax.Array]:
    loss, grads = jax.value_and_grad(_loss)(params, windows, labels)
    new = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return new, loss


def make_mesh_train_step(mesh: jax.sharding.Mesh):
    """A jitted training step laid out over *mesh*: batches sharded on
    the 'data' axis, parameters replicated; the partitioner inserts the
    gradient all-reduce."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_sharding = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())

    step = jax.jit(
        train_step,
        in_shardings=(
            jax.tree_util.tree_map(lambda _: repl,
                                   HealthModel(*([None] * 6))),
            data_sharding, data_sharding),
        out_shardings=(
            jax.tree_util.tree_map(lambda _: repl,
                                   HealthModel(*([None] * 6))),
            repl),
        static_argnums=(3,),
    )
    return step, data_sharding, repl


def synthetic_batch(key: jax.Array, batch: int
                    ) -> tuple[jax.Array, jax.Array]:
    """Training data in the REAL normalized feature space produced by
    telemetry.TelemetryRing (features: latency, timed_out, lag, wal
    stall, reconnect flaps, each ~[0,1] — see telemetry.normalize_tick).

    Healthy peers: small latencies (a few ms..tens of ms), no timeouts,
    near-zero lag, no stall, no flaps.  Degrading peers: latency and lag
    ramp across the window, timeouts and WAL stalls appear with rising
    probability, occasional flaps — the signature of a database heading
    for its hard healthChkTimeout.
    """
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    labels = (jax.random.uniform(k2, (batch,)) > 0.5).astype(jnp.float32)
    lab = labels[:, None]
    trend = jnp.linspace(0.0, 1.0, WINDOW)[None, :]           # [1, W]
    noise = jax.random.uniform(k1, (batch, WINDOW, N_FEATURES))

    latency = 0.005 + 0.03 * noise[..., 0] \
        + lab * trend * (0.3 + 0.7 * jax.random.uniform(k3, (batch, 1)))
    p_timeout = lab * trend * 0.6
    timed_out = (noise[..., 1] < p_timeout).astype(jnp.float32)
    lag = 0.01 * noise[..., 2] \
        + lab * trend * (0.4 + 0.6 * jax.random.uniform(k4, (batch, 1)))
    stall = (noise[..., 3] < lab * trend * 0.5).astype(jnp.float32)
    flaps = jnp.minimum(
        lab * trend * jax.random.uniform(k5, (batch, 1)) * 0.8
        + 0.02 * noise[..., 4], 1.0)

    windows = jnp.stack(
        [jnp.clip(latency, 0.0, 1.0), timed_out,
         jnp.clip(lag, 0.0, 1.0), stall, flaps], axis=-1)

    # Deployed-cadence masking: the manager attaches the status op
    # (lag/stall observations) only to every STATUS_EVERY-th SUCCESSFUL
    # probe; the ring carries the last observation across the other
    # ticks (telemetry.TelemetryRing.add).  Training on dense windows
    # while deployment scores sparse+carried ones is a distribution
    # mismatch that costs real detection — emulate the cadence here
    # with a random phase per window and a carry-forward scan.
    k6 = jax.random.fold_in(k1, 7)
    phase = jax.random.randint(k6, (batch, 1), 0, STATUS_EVERY)
    pos = jnp.arange(WINDOW)[None, :]
    has_status = ((pos % STATUS_EVERY) == phase) & (timed_out < 0.5)

    def carry(prev, x):
        obs, has = x                      # [batch, 2], [batch]
        cur = jnp.where(has[:, None], obs, prev)
        return cur, cur

    obs_seq = jnp.stack([windows[..., 2], windows[..., 3]],
                        axis=-1).swapaxes(0, 1)       # [W, batch, 2]
    init = jnp.zeros((batch, 2))
    _, carried = jax.lax.scan(carry, init,
                              (obs_seq, has_status.swapaxes(0, 1)))
    carried = carried.swapaxes(0, 1)                  # [batch, W, 2]
    windows = windows.at[..., 2].set(carried[..., 0])
    windows = windows.at[..., 3].set(carried[..., 1])

    # Restart masking: the deployed ring starts scoring at window//2
    # ticks (telemetry.ready), zero-padding the OLD end
    # (window_array) — so for the first half-window after every
    # sitter/database restart the scorer sees leading all-zero rows.
    # Train on that shape too (leading zeros on a random ~third of
    # windows, pad length up to the ready() minimum) or those ticks
    # are scored on a distribution the model never saw, exactly when
    # spurious "degrading" notices are most misleading
    # (code-review r5).
    k7 = jax.random.fold_in(k1, 11)
    k8 = jax.random.fold_in(k1, 13)
    pad_on = jax.random.uniform(k7, (batch, 1)) < 0.35
    pad_len = jax.random.randint(k8, (batch, 1), 1,
                                 WINDOW - WINDOW // 2 + 1)
    keep = pos >= jnp.where(pad_on, pad_len, 0)        # [batch, W]
    windows = windows * keep[..., None]
    return windows, labels
