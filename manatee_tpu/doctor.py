"""Store integrity verification for `manatee-adm doctor`.

The crash-recovery sweep (docs/crash-recovery.md) crashes a daemon at
every cataloged failpoint and restarts it on the same data dir; doctor
is the judge that the stores it recovered from — and the ones it left
behind — are sound.  Three families of checks, all READ-ONLY (doctor
must be safe to run against a live shard and must never "helpfully"
repair what an operator needs to inspect):

- **coordd store** (``--coord-data``): the snapshot + op-log layout of
  coord/server.py, verified by replaying it exactly as recovery would,
  without mutating a byte.  A torn final line of the final segment is
  *classified* (crash mid-append; necessarily unacked; recovery
  truncates it) and reported as a note, NOT damage — distinguishing it
  from mid-stream corruption, seq gaps, replay divergence, and
  malformed snapshots, all of which mean acked writes are at risk and
  the server itself would refuse to start.
- **dirstore** (``--store-root``): dataset shape (@data/@snapshots/
  @meta.json), meta parseability (the empty/truncated meta an
  un-fsynced tmp-rename crash used to install), the
  dataset↔meta cross-check: every snapshot meta names must exist on
  disk, every on-disk snapshot should be in meta (an orphan dir is the
  crash window between copytree and meta install — recoverable,
  reported as a warning), and the manifest↔snapshot cross-check: each
  @manifests/<name>.json must structurally agree (paths, types, sizes,
  link targets) with its snapshot directory.  The manifest is the
  delta plane's ground truth for incremental-rebuild eligibility — a
  PARSEABLE manifest that diverges from its immutable snapshot could
  ship (and verify!) a wrong delta, so divergence is damage, while an
  unreadable manifest merely forces a lazy recompute (warning) and a
  manifest for a destroyed snapshot is sweepable debris (note).
- **cluster state** (online): schema shape of the state object,
  generation monotonicity across the durable history, and agreement
  with the event journal (a journal that has seen a HIGHER generation
  than the stored state means the store rolled back an acked
  transition).

Findings carry a severity: ``damage`` (acked data at risk — nonzero
exit), ``warning`` (recoverable inconsistency worth an operator's
look), ``note`` (expected crash leftovers recovery cleans).  Every
check function here is pure/synchronous so it can run offline, in
tests, and under ``asyncio.to_thread`` from the CLI alike.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from manatee_tpu.storage.dirstore import (
    META_KEYS,
    _RESERVED,
    manifest_diff_paths,
    manifest_scan,
)

DAMAGE = "damage"
WARNING = "warning"
NOTE = "note"


def finding(level: str, check: str, target: str, detail: str) -> dict:
    return {"level": level, "check": check, "target": str(target),
            "detail": detail}


def summarize(findings: list[dict]) -> dict:
    counts = {DAMAGE: 0, WARNING: 0, NOTE: 0}
    for f in findings:
        counts[f["level"]] += 1
    return {"findings": findings, "damage": counts[DAMAGE],
            "warnings": counts[WARNING], "notes": counts[NOTE],
            "ok": counts[DAMAGE] == 0}


# ---- coordd store ----

def _snapshot_stamp(d: Path):
    """Identity of the installed snapshot, for the live-compaction
    retry: every segment deletion coordd performs is preceded by a
    snapshot install (a rename, so a new inode), so an unchanged stamp
    across a scan proves the scan saw a consistent store."""
    try:
        st = (d / "coordd-tree.json").stat()
        return (st.st_ino, st.st_mtime_ns, st.st_size)
    except OSError:
        return None


def check_coordd_store(data_dir: str | Path) -> list[dict]:
    """Verify a coordd --data-dir the way server recovery would load
    it, read-only: snapshot shape, current-epoch segment replay with
    seq continuity and acked-result agreement, torn-tail
    classification, and crash-leftover (stale epoch / tmp snapshot)
    accounting.

    Safe against a LIVE coordd: the snapshot and the segments are read
    non-atomically, and a compaction landing between the two reads
    would make a healthy store look gap-damaged (the new snapshot
    supersedes segments the scan already planned on).  The scan
    retries while the snapshot identity moved underneath it."""
    d = Path(data_dir)
    out: list[dict] = []
    for _attempt in range(3):
        before = _snapshot_stamp(d)
        out = _scan_coordd_store(d)
        if _snapshot_stamp(d) == before:
            break           # nothing moved: the scan was consistent
    return out


def _scan_coordd_store(d: Path) -> list[dict]:
    # imported lazily so an offline dirstore-only doctor run does not
    # pull the whole coordination stack.  parse_segment_name /
    # snapshot_shape_ok / _apply_wire_op / _seed_seq_counters come
    # from the SERVER so the on-disk contract this verifier enforces
    # is the writer's own code, never a drifting copy.
    from manatee_tpu.coord import model
    from manatee_tpu.coord.api import CoordError
    from manatee_tpu.coord.server import (
        _apply_wire_op,
        _seed_seq_counters,
        parse_segment_name,
        snapshot_shape_ok,
    )

    out: list[dict] = []
    if not d.is_dir():
        out.append(finding(DAMAGE, "coord-dir-missing", d,
                           "data dir does not exist"))
        return out

    snap_path = d / "coordd-tree.json"
    tree = model.ZNodeTree()
    seq = 0
    epoch: int | None = None
    if snap_path.exists():
        try:
            snap = json.loads(snap_path.read_text())
            if not snapshot_shape_ok(snap):
                raise ValueError("unrecognized snapshot shape")
            tree = model.ZNodeTree.from_snapshot(snap)
            seq = int(snap["seq"])
            epoch = int(snap["epoch"])
        except (ValueError, OSError, KeyError, TypeError) as e:
            out.append(finding(
                DAMAGE, "coord-snapshot-corrupt", snap_path,
                "snapshot exists but cannot be loaded (%s); the "
                "server would refuse to start" % e))
            return out

    segs: list[tuple[int, int, Path]] = []
    for p in d.glob("coordd-oplog-*.jsonl"):
        key = parse_segment_name(p)
        if key is None:
            out.append(finding(NOTE, "oplog-unrecognized-name", p,
                               "unparseable segment name (startup "
                               "removes it as stale)"))
            continue
        segs.append((key[0], key[1], p))
    if epoch is None:
        epoch = max((e for e, _s, _p in segs), default=0)
    stale = [p for e, _s, p in segs if e != epoch]
    for p in sorted(stale):
        out.append(finding(NOTE, "oplog-stale-epoch", p,
                           "segment from epoch superseded by a resync "
                           "snapshot (startup removes it)"))
    for p in sorted(d.glob("coordd-tree.json.tmp*")):
        out.append(finding(NOTE, "snapshot-tmp-orphan", p,
                           "snapshot tmp file a crashed compaction "
                           "never installed (startup removes it)"))

    current = sorted(((s, p) for e, s, p in segs if e == epoch))
    paths = [p for _s, p in current]
    for i, path in enumerate(paths):
        try:
            raw = path.read_bytes()
        except OSError as e:
            out.append(finding(DAMAGE, "oplog-unreadable", path,
                               str(e)))
            return out
        nonempty = [part for part in raw.split(b"\n") if part]
        for j, line in enumerate(nonempty):
            last_line = (i == len(paths) - 1
                         and j == len(nonempty) - 1)
            try:
                ent = json.loads(line)
                entry_seq = int(ent["seq"])
                req = ent["req"]
            except (ValueError, KeyError, TypeError):
                if last_line:
                    out.append(finding(
                        NOTE, "oplog-torn-tail", path,
                        "final line is torn (crash mid-append; it "
                        "was never acked — recovery truncates it)"))
                    break
                out.append(finding(
                    DAMAGE, "oplog-corrupt", path,
                    "unparseable entry mid-stream (line %d of the "
                    "non-empty lines); acked writes would be lost"
                    % (j + 1)))
                return out
            if entry_seq <= seq:
                continue            # covered by the snapshot
            if entry_seq != seq + 1:
                out.append(finding(
                    DAMAGE, "oplog-gap", path,
                    "entry seq %d follows %d; acked writes in the "
                    "gap are gone" % (entry_seq, seq)))
                return out
            expect = ent.get("expect")
            try:
                _seed_seq_counters(tree, req, expect)
                got = _apply_wire_op(tree, req)
            except CoordError as e:
                out.append(finding(
                    DAMAGE, "oplog-apply-failed", path,
                    "entry seq %d does not apply (%s)"
                    % (entry_seq, e)))
                return out
            if "expect" in ent and got != expect:
                out.append(finding(
                    DAMAGE, "oplog-diverged", path,
                    "replaying seq %d produced %r but %r was acked"
                    % (entry_seq, got, expect)))
                return out
            seq = entry_seq
    return out


# ---- dirstore ----

def _dataset_dirs(root: Path) -> list[Path]:
    """Every directory under datasets/ that looks like a dataset (has
    any of the reserved members), deepest-last."""
    base = root / "datasets"
    out = []
    if not base.is_dir():
        return out
    for dirpath, dirnames, filenames in os.walk(base):
        members = set(dirnames) | set(filenames)
        # never descend into dataset CONTENT (restored pg trees can be
        # arbitrarily deep and could even contain reserved names)
        dirnames[:] = [n for n in dirnames
                       if n not in ("@data", "@snapshots",
                                    "@manifests")]
        if members & _RESERVED:
            out.append(Path(dirpath))
    out.sort()
    return out


def check_dirstore(root: str | Path) -> list[dict]:
    """Verify a dir-backend store root: per-dataset shape, meta
    parseability, and the dataset↔meta snapshot cross-check."""
    root = Path(root)
    out: list[dict] = []
    if not (root / "datasets").is_dir():
        out.append(finding(WARNING, "no-datasets-dir", root,
                           "not a dir-backend store root (no "
                           "datasets/ directory)"))
        return out
    for ds in _dataset_dirs(root):
        rel = ds.relative_to(root / "datasets")
        meta_path = ds / "@meta.json"
        for tmp in sorted(ds.glob("@meta.json.tmp*")):
            out.append(finding(NOTE, "meta-tmp-orphan", tmp,
                               "tmp meta a crashed save never "
                               "installed (safe to remove)"))
        if not meta_path.exists():
            out.append(finding(DAMAGE, "meta-missing", ds,
                               "dataset %s has content but no "
                               "@meta.json" % rel))
            continue
        try:
            meta = json.loads(meta_path.read_text())
            if not isinstance(meta, dict):
                raise ValueError("meta is not an object")
        except (ValueError, OSError) as e:
            out.append(finding(
                DAMAGE, "meta-corrupt", meta_path,
                "unreadable/unparseable @meta.json (%s) — the "
                "empty/truncated install a non-fsynced tmp rename "
                "leaves after a crash" % e))
            continue
        missing = [k for k in META_KEYS if k not in meta]
        if missing:
            out.append(finding(DAMAGE, "meta-malformed", meta_path,
                               "missing keys: %s" % ", ".join(missing)))
            continue
        if not (ds / "@data").is_dir():
            out.append(finding(DAMAGE, "data-missing", ds,
                               "dataset %s has no @data directory"
                               % rel))
        snapdir = ds / "@snapshots"
        if not snapdir.is_dir():
            out.append(finding(DAMAGE, "snapdir-missing", ds,
                               "dataset %s has no @snapshots "
                               "directory" % rel))
            continue
        snaps_meta = meta.get("snaps")
        if not isinstance(snaps_meta, dict):
            out.append(finding(DAMAGE, "meta-malformed", meta_path,
                               "snaps is not an object"))
            continue
        on_disk = {p.name for p in snapdir.iterdir() if p.is_dir()}
        for name in sorted(set(snaps_meta) - on_disk):
            out.append(finding(
                DAMAGE, "snapshot-missing", ds,
                "meta records snapshot %r but @snapshots/%s does "
                "not exist" % (name, name)))
        for name in sorted(on_disk - set(snaps_meta)):
            out.append(finding(
                WARNING, "snapshot-orphan", snapdir / name,
                "snapshot directory not recorded in meta (crash "
                "between copy and meta install; safe to remove)"))
        if meta.get("mounted"):
            mp = meta.get("mountpoint")
            target = str((ds / "@data").resolve())
            if not mp or not Path(mp).is_symlink() \
                    or os.path.realpath(mp) != target:
                out.append(finding(
                    WARNING, "mount-stale", ds,
                    "meta says mounted but the mountpoint symlink "
                    "is absent or points elsewhere (is_mounted "
                    "treats the symlink as ground truth)"))
        if meta.get("applying"):
            out.append(finding(
                NOTE, "delta-apply-in-progress", ds,
                "half-applied incremental restore (crash mid-apply); "
                "the restore plane sweeps it and retries full"))
        out.extend(_check_manifests(ds, rel, on_disk))
    return out


def _check_manifests(ds: Path, rel, on_disk: set) -> list[dict]:
    """The manifest↔snapshot cross-check: incremental-rebuild
    eligibility ground truth.  Structural (paths/types/sizes/modes/
    link targets, no hashing): snapshot dirs are immutable after creation,
    so ANY disagreement means the manifest lies about what a delta
    sender would ship — and a lying manifest can produce a delta that
    verifies against itself while diverging from the real snapshot."""
    out: list[dict] = []
    mandir = ds / "@manifests"
    if not mandir.is_dir():
        return out          # pre-manifest dataset: backfilled lazily
    for tmp in sorted(mandir.glob("*.json.tmp*")):
        out.append(finding(NOTE, "manifest-tmp-orphan", tmp,
                           "tmp manifest a crashed write never "
                           "installed (safe to remove)"))
    for mf in sorted(mandir.glob("*.json")):
        name = mf.name[:-5]
        if name not in on_disk:
            out.append(finding(
                NOTE, "manifest-orphan", mf,
                "manifest for a snapshot that no longer exists "
                "(destroyed mid-GC; safe to remove)"))
            continue
        try:
            man = json.loads(mf.read_text())
            files = man["files"]
            if not isinstance(files, dict):
                raise ValueError("files is not an object")
        except (ValueError, KeyError, OSError) as e:
            out.append(finding(
                WARNING, "manifest-corrupt", mf,
                "unreadable/unparseable manifest (%s) — lazily "
                "recomputed from the snapshot dir on next use" % e))
            continue
        scan = manifest_scan(ds / "@snapshots" / name, with_hash=False)
        bad = manifest_diff_paths(scan, files, with_hash=False)
        if bad:
            out.append(finding(
                DAMAGE, "manifest-diverged", mf,
                "manifest disagrees with the (immutable) snapshot "
                "directory of %s@%s at %d path(s) (first: %s) — a "
                "delta sent from it could install wrong content; "
                "remove the manifest so it is recomputed"
                % (rel, name, len(bad), ", ".join(bad[:5]))))
    return out


# ---- metric-history segment ring (obs/history.py) ----

def check_history(directory: str | Path) -> list[dict]:
    """Verify a metric-history ring the way the reader/writer would
    load it, read-only: segment naming, per-line parseability, seq
    continuity across the retained records.

    The writer's crash discipline (append → flush → fsync, resume from
    the last DURABLE record) means a crash can leave exactly one
    signature: a torn final line — possibly mid-ring, because the
    restarted writer opens a fresh segment rather than appending after
    a tear.  Torn tails and empty segments are notes; an unparseable
    line with parseable lines after it, a seq gap/regression, or a
    segment whose name disagrees with its first record mean durable
    records were altered or lost: damage."""
    d = Path(directory)
    out: list[dict] = []
    if not d.is_dir():
        out.append(finding(WARNING, "history-dir-missing", d,
                           "history directory does not exist (ring "
                           "never enabled, or wrong path)"))
        return out
    # the writer's own naming/reading code, never a drifting copy
    from manatee_tpu.obs.history import (
        SEGMENT_PREFIX,
        list_segments,
        parse_segment_name,
    )
    for p in sorted(d.glob(SEGMENT_PREFIX + "*")):
        if parse_segment_name(p) is None:
            out.append(finding(NOTE, "history-unrecognized-name", p,
                               "unparseable segment name (not part "
                               "of the ring)"))
    segs = list_segments(d)
    if not segs:
        out.append(finding(NOTE, "history-empty", d,
                           "no history segments (ring enabled but "
                           "nothing recorded yet)"))
        return out
    last_seq: int | None = None
    for path in segs:
        try:
            raw = path.read_bytes()
        except OSError as e:
            out.append(finding(DAMAGE, "history-unreadable", path,
                               str(e)))
            return out
        nonempty = [part for part in raw.split(b"\n") if part.strip()]
        if not nonempty:
            out.append(finding(NOTE, "history-empty-segment", path,
                               "segment has no records (crash between "
                               "rotate and first append)"))
            continue
        first_in_seg = True
        for j, line in enumerate(nonempty):
            try:
                rec = json.loads(line)
                seq = int(rec["seq"])
            except (ValueError, KeyError, TypeError):
                if j == len(nonempty) - 1:
                    # a tear is legal at the END of any segment: the
                    # restarted writer rotates rather than appending
                    # after one, so tears persist mid-ring
                    out.append(finding(
                        NOTE, "history-torn-tail", path,
                        "final line is torn (crash mid-append; the "
                        "record was never durable — readers skip it)"))
                    break
                out.append(finding(
                    DAMAGE, "history-corrupt", path,
                    "unparseable record mid-stream (line %d of the "
                    "non-empty lines); durable records were altered"
                    % (j + 1)))
                return out
            if first_in_seg:
                first_in_seg = False
                named = parse_segment_name(path)
                if named != seq:
                    out.append(finding(
                        DAMAGE, "history-misnamed", path,
                        "segment name says first seq %s but the first "
                        "record is seq %d" % (named, seq)))
                    return out
            if last_seq is not None and seq != last_seq + 1:
                out.append(finding(
                    DAMAGE, "history-gap", path,
                    "record seq %d follows %d; durable snapshots in "
                    "between are gone" % (seq, last_seq)))
                return out
            last_seq = seq
    return out


# ---- cluster state vs history vs journal (online) ----

def check_cluster(state: dict | None, history: list[dict],
                  events: list[dict]) -> list[dict]:
    """Pure checks over already-fetched cluster data: state schema,
    generation monotonicity across the durable history, and journal
    agreement (no peer's event ring may have seen a generation the
    store has since lost)."""
    out: list[dict] = []
    if state is None:
        out.append(finding(WARNING, "state-missing", "cluster",
                           "no cluster state object (uninitialized "
                           "shard?)"))
        return out
    bad = []
    if not isinstance(state.get("generation"), int) \
            or state["generation"] < 0:
        bad.append("generation")
    if not isinstance(state.get("primary"), dict) \
            or not state["primary"].get("id"):
        bad.append("primary")
    if "initWal" not in state:
        bad.append("initWal")
    for key in ("async", "deposed"):
        if state.get(key) is not None \
                and not isinstance(state.get(key), list):
            bad.append(key)
    if bad:
        out.append(finding(DAMAGE, "state-schema", "cluster",
                           "state object malformed: %s"
                           % ", ".join(bad)))
        return out
    gens = [(h.get("zkSeq"), h.get("generation")) for h in history
            if isinstance(h.get("generation"), int)]
    gens.sort(key=lambda t: (t[0] is None, t[0]))
    last = None
    for zkseq, g in gens:
        if last is not None and g < last:
            out.append(finding(
                DAMAGE, "generation-regression", "cluster",
                "history generation went backwards (%d after %d at "
                "coordination seq %s)" % (g, last, zkseq)))
        last = g
    if last is not None and state["generation"] < last:
        out.append(finding(
            DAMAGE, "generation-regression", "cluster",
            "stored state is at generation %d but the history has "
            "seen %d — the store rolled back an acked transition"
            % (state["generation"], last)))
    # transition.committed ONLY: begin is journaled with the ATTEMPTED
    # generation before the CAS write, and a lost race / connection
    # error legitimately leaves a begin at g+1 in some ring with the
    # store correctly still at g — only a committed event proves the
    # write was acked
    seen = [e.get("generation") for e in events
            if e.get("event") == "transition.committed"
            and isinstance(e.get("generation"), int)]
    if seen and max(seen) > state["generation"]:
        out.append(finding(
            DAMAGE, "journal-generation-ahead", "cluster",
            "a peer's event journal has seen generation %d but the "
            "stored state is at %d — the store rolled back an acked "
            "transition" % (max(seen), state["generation"])))
    return out


# ---- runtime <-> static cross-check (obs/profile.py's monitor) ----

def check_introspection(events: list[dict]) -> list[dict]:
    """Pure checks over the merged event journal's loop-health
    records.  An ``obs.lint.discrepancy`` means the blocked-loop
    watchdog caught a stack stalling the event loop that the static
    side cannot account for: either mnt-lint's blocking rules were
    told to ignore the frame (``via`` = path-disable / suppression),
    or the culprit is not derivable from the interprocedural
    may-block summaries at all (``via=not-derived`` — the call graph
    has a hole: a dynamic dispatch, an extension module, or a catalog
    gap).  Raw ``obs.loop.stall`` events are NOTEs: real, but already
    on `manatee-adm top`'s STALLS column; the discrepancy is the
    actionable finding."""
    out: list[dict] = []
    seen: set = set()
    stalls: dict[str, int] = {}
    worst: dict[str, float] = {}
    for ev in events or []:
        name = ev.get("event")
        if name == "obs.lint.discrepancy":
            key = (ev.get("file"), ev.get("line"), ev.get("rule"))
            if key in seen:
                continue
            seen.add(key)
            if (ev.get("via") or "") == "not-derived":
                out.append(finding(
                    WARNING, "lint-underived-stall",
                    "%s:%s" % (ev.get("file"), ev.get("line")),
                    "the event loop stalled inside %s(), but no "
                    "may-block summary derives a blocking chain "
                    "there — the static analysis is blind to this "
                    "stall (dynamic dispatch, extension code, or a "
                    "blocking-catalog gap); teach lint/summaries.py "
                    "about the edge or catalog the primitive"
                    % ev.get("func")))
                continue
            out.append(finding(
                WARNING, "lint-exemption-blocks",
                "%s:%s" % (ev.get("file"), ev.get("line")),
                "the event loop stalled inside %s(), but the %s "
                "rule is exempted there via %s — the static "
                "exemption hides a real blocking call; fix the "
                "call or drop the exemption"
                % (ev.get("func"), ev.get("rule") or "blocking-call",
                   ev.get("via") or "suppression")))
        elif name == "obs.loop.stall":
            peer = ev.get("peer") or "?"
            stalls[peer] = stalls.get(peer, 0) + 1
            try:
                blocked = float(ev.get("blocked_s") or 0.0)
            except (TypeError, ValueError):
                blocked = 0.0
            worst[peer] = max(worst.get(peer, 0.0), blocked)
    for peer in sorted(stalls):
        out.append(finding(
            NOTE, "loop-stalls", peer,
            "%d event-loop stall(s) journaled (worst %.3fs); "
            "`manatee-adm events -e obs.loop.stall` has the "
            "captured stacks" % (stalls[peer], worst[peer])))
    return out


# ---- wall-clock skew vs the journal-merge safety bound ----

def check_skew(skew: dict | None) -> list[dict]:
    """Pure check over measured per-peer clock offsets (the fan-out's
    ``skew`` map / ``clock_skew_seconds{peer}``): warn when a peer's
    skew exceeds :data:`~manatee_tpu.obs.causal.MERGE_SKEW_BOUND_S`.
    HLC-stamped records merge correctly at ANY skew; the bound exists
    for records from pre-HLC peers, which merge on wall clocks alone
    — past it, their cause-and-effect ordering in `manatee-adm
    events` (and the incident analyzer's timeline) is no longer
    trustworthy."""
    from manatee_tpu.obs.causal import MERGE_SKEW_BOUND_S
    out: list[dict] = []
    for peer in sorted(skew or {}):
        try:
            off = float(skew[peer])
        except (TypeError, ValueError):
            continue
        if abs(off) > MERGE_SKEW_BOUND_S:
            out.append(finding(
                WARNING, "skew-exceeds-merge-bound", peer,
                "measured wall-clock skew %+.3fs exceeds the "
                "journal-merge safety bound (%.1fs): records from "
                "pre-HLC peers merge on wall clocks alone and may "
                "misorder cause and effect; HLC-stamped records are "
                "unaffected" % (off, MERGE_SKEW_BOUND_S)))
    return out


# ---- the key-range shard map (reshard/plan.py) ----

def check_shard_map(map_obj: dict | None, record: dict | None,
                    holds: list[str] | None = None) -> list[dict]:
    """Pure checks over the shard map, the reshard step record, and
    any discovered boot-hold nodes.  An invalid map is DAMAGE (a key
    range with zero or two owners breaks the routing invariant); a
    ``frozen`` range with no live reshard record is DAMAGE too (the
    cutover that froze it is gone, and routers will park its writes
    forever).  A ``done`` record is a NOTE (history, overwritten by
    the next reshard); a boot hold with no live record is a WARNING
    (sitters under that shardPath are parked waiting on a resharder
    that no longer exists)."""
    out: list[dict] = []
    live = record is not None \
        and record.get("step") not in ("done", "aborted")
    if map_obj is not None:
        from manatee_tpu.reshard.plan import (
            FROZEN,
            ShardMapError,
            validate_map,
        )
        try:
            validate_map(map_obj)
        except ShardMapError as e:
            out.append(finding(
                DAMAGE, "shardmap-invalid", "shardmap",
                "the shard map violates the one-owner-per-range "
                "invariant: %s" % e))
            return out
        for r in map_obj["ranges"]:
            if r["state"] == FROZEN and not live:
                out.append(finding(
                    DAMAGE, "shardmap-frozen-orphan", r["shard"],
                    "range [%r, %r) is frozen but no reshard is in "
                    "flight — routers park its writes forever; "
                    "restore it with a map CAS back to 'serving' "
                    "(or `manatee-adm reshard --resume` if a record "
                    "reappears)" % (r["lo"], r["hi"])))
    if record is not None and not live:
        out.append(finding(
            NOTE, "reshard-record-finished", "shardmap",
            "the last reshard (%s) finished at step %r; the record "
            "is history and the next `manatee-adm reshard` "
            "overwrites it" % (record.get("op", "?"),
                               record.get("step"))))
    elif live:
        out.append(finding(
            NOTE, "reshard-in-flight", "shardmap",
            "reshard %s is at step %r — resume or abort it with "
            "`manatee-adm reshard`" % (record.get("op", "?"),
                                       record.get("step"))))
    for path in holds or []:
        if not live:
            out.append(finding(
                WARNING, "reshard-hold-orphan", path,
                "a reshard boot hold exists with no reshard in "
                "flight: sitters booting under that shardPath are "
                "parked until the node is deleted"))
    return out
